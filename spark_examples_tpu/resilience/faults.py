"""Deterministic, seedable fault-injection plane.

Until this round the only fault injection was an inline test hack in
``FixtureSource`` (a set of shards that raise once). That cannot
exercise the failure modes a production ingest run actually meets —
mid-stream truncation, wire corruption, stalled lanes, torn checkpoint
writes — and it cannot compose them. This module is the one place
faults come from:

- a :class:`FaultPlan` is a SEEDED list of declarative
  :class:`FaultRule`\\ s, activatable per process (CLI ``--fault-plan``,
  env ``SPARK_EXAMPLES_TPU_FAULT_PLAN``) or per scope
  (:func:`active_plan`);
- production code carries *injection points* — :func:`inject` calls at
  transport, shard-ingest, and checkpoint/lane seams — that are a
  single ``None``-check when no plan is installed (the telemetry-off
  contract, applied to chaos);
- every injected fault is recorded on the plan (test introspection),
  the obs timeline (``fault_injected`` instants), and the metrics
  registry (``resilience_faults_injected_total{site,kind}``), so a
  chaos run's artifacts SHOW what was injected — the property the
  chaos harness asserts through ``scripts/validate_trace.py``.

Determinism: rule matching is by site/key and a per-rule eligible-hit
counter; probabilistic rules draw from ``hash((seed, rule, hit))`` so
the SAME plan over the same request sequence injects the same faults.
(Under thread-parallel ingest the assignment of hits to shards can vary
with interleaving; the chaos harness's correctness bar — results
identical to the fault-free run — holds regardless, which is the
point.)

Sites wired in this round (glob-matched, so ``transport.*`` works):

==========================  =================================================
``transport.http.request``  before each HTTP attempt (error/stall)
``transport.http.stream``   HTTP shard-stream body (error/stall/truncate/
                            corrupt — detected by the framing layer)
``transport.grpc.request``  before each gRPC unary/stream attempt
``transport.grpc.stream``   gRPC stream body (same four kinds)
``transport.oauth.request`` before each token-exchange attempt
``ingest.shard``            driver-side shard extraction (error = worker
                            death mid-stream, stall = slow lane)
``ingest.stream``           fused-CSR streaming ingest, per shard inside
                            the retry loop (error/stall/truncate = a
                            fetch-decode-build-put pipeline fault
                            mid-stream; retried per --shard-retries)
``mirror.write``            cohort-mirror file commit (torn = kill -9
                            mid-write: the tmp truncates to half and
                            never renames; error/stall as usual)
``checkpoint.snapshot_write``  Gramian snapshot save (torn/error/stall)
``checkpoint.lane_write``      elastic lane save (torn/error/stall)
``checkpoint.lane_supersede``  crash between lane write and stale-lane
                               delete (leaves stale subset lanes)
``fixture.stream``          FixtureSource per-shard streams (the migrated
                            ``fail_shards`` hook)
``store.read``              durable-store object reads (error/stall)
``store.write``             durable-store object puts (torn = kill -9
                            mid-write: the framed blob truncates under its
                            ``.tmp-`` name and never renames)
``store.lease``             lease CAS operations, keyed ``<op>:<name>``
                            (error/stall; ``corrupt`` is locally
                            interpreted as a STALE FENCING TOKEN — the
                            op raises ``FencedWriteError``, the zombie-
                            write shape)
``store.lease.write``       lease-doc file commit (torn = kill -9 between
                            fsync and rename: reads back as "no lease"
                            with the token floor intact)
``serving.delta.write``     delta-cache persist-dir commit (torn = the
                            ``.tmp-`` partial the next load sweeps; the
                            entry stays memory-only)
``flightrec.write``         crash flight-recorder dump commit (torn =
                            kill mid-dump; the previous dump under the
                            final name survives untouched)
==========================  =================================================

The serving seams (``serving.job.run``/``serving.job.kill``/
``serving.journal.append``) and the store seams' failure semantics are
documented in docs/RESILIENCE.md.
"""

from __future__ import annotations

import contextlib
import fnmatch
import json
import os
import random
import threading
import time
from dataclasses import asdict, dataclass
from typing import Iterator, List, Optional, Sequence

__all__ = [
    "FAULT_PLAN_ENV",
    "FaultPlan",
    "FaultRule",
    "InjectedFault",
    "active_plan",
    "clear_plan",
    "current_plan",
    "inject",
    "inject_write",
    "install_plan",
    "plan_from_env",
    "take",
    "wrap_lines",
]

FAULT_PLAN_ENV = "SPARK_EXAMPLES_TPU_FAULT_PLAN"

KINDS = ("error", "stall", "truncate", "corrupt", "torn")


class InjectedFault(IOError):
    """A fault the plan injected (an IOError: transports and the shard
    retry layer already classify it as IO weather)."""

    def __init__(self, site: str, kind: str, key: str = "", message: str = ""):
        text = message or f"injected {kind} fault at {site}"
        if key:
            text += f" (key={key})"
        super().__init__(text)
        self.site = site
        self.kind = kind
        self.key = key


@dataclass
class FaultRule:
    """One declarative fault.

    ``site`` glob-matches the injection point; ``match`` (substring of
    the site key, e.g. a shard string) narrows it. ``times`` caps how
    often the rule fires (None = unbounded), ``after`` skips the first
    N eligible hits, ``probability`` gates each remaining hit through a
    seeded draw. Stream-shaped kinds (truncate/corrupt, applied by
    :func:`wrap_lines`) act at line index ``at_line``.
    """

    site: str
    kind: str = "error"
    probability: float = 1.0
    times: Optional[int] = 1
    after: int = 0
    match: str = ""
    stall_s: float = 0.05
    at_line: int = 0
    message: str = ""

    def __post_init__(self) -> None:
        if self.kind not in KINDS:
            raise ValueError(
                f"unknown fault kind {self.kind!r}; expected one of {KINDS}"
            )
        if not (0.0 <= self.probability <= 1.0):
            raise ValueError("probability must be in [0, 1]")


@dataclass
class _Fired:
    """One injected fault, kept on the plan for introspection."""

    site: str
    kind: str
    key: str = ""


class FaultPlan:
    """A seeded set of rules plus their runtime counters (thread-safe)."""

    MAX_LOG = 10_000  # bound the introspection log on long soaks

    def __init__(self, seed: int = 0, rules: Sequence[FaultRule] = ()):
        self.seed = int(seed)
        self._rules: List[FaultRule] = list(rules)
        self._lock = threading.Lock()
        self._hits: List[int] = [0] * len(self._rules)
        self._count: List[int] = [0] * len(self._rules)
        self.injected: List[_Fired] = []

    # -- construction ---------------------------------------------------------

    def add_rule(self, rule: FaultRule) -> None:
        with self._lock:
            self._rules.append(rule)
            self._hits.append(0)
            self._count.append(0)

    @classmethod
    def from_dict(cls, spec: dict) -> "FaultPlan":
        rules = [FaultRule(**r) for r in spec.get("rules", ())]
        return cls(seed=int(spec.get("seed", 0)), rules=rules)

    @classmethod
    def from_spec(cls, spec: str) -> "FaultPlan":
        """CLI/env value → plan: a JSON object inline, or a path to a
        JSON file holding one."""
        text = spec.strip()
        if not text.startswith("{"):
            with open(text) as f:
                text = f.read()
        try:
            doc = json.loads(text)
        except ValueError as e:
            raise ValueError(f"unparseable fault plan {spec!r}: {e}") from e
        if not isinstance(doc, dict):
            raise ValueError("fault plan must be a JSON object")
        return cls.from_dict(doc)

    def to_dict(self) -> dict:
        with self._lock:
            return {
                "seed": self.seed,
                "rules": [asdict(r) for r in self._rules],
            }

    # -- runtime --------------------------------------------------------------

    @property
    def fired_total(self) -> int:
        with self._lock:
            return sum(self._count)

    def fired_by_site(self) -> dict:
        with self._lock:
            out: dict = {}
            for f in self.injected:
                out[f.site] = out.get(f.site, 0) + 1
            return out

    def inject(self, site: str, key: str = "") -> None:
        """Per-instance injection point (the ambient plan untouched):
        same action semantics as the module-level :func:`inject`."""
        inject(site, key, plan=self)

    def decide(self, site: str, key: str = "") -> Optional[FaultRule]:
        """First matching rule that fires for this hit, with counters
        advanced and the injection recorded; None = no fault here."""
        with self._lock:
            for i, rule in enumerate(self._rules):
                if not fnmatch.fnmatchcase(site, rule.site):
                    continue
                if rule.match and rule.match not in key:
                    continue
                hit = self._hits[i]
                self._hits[i] += 1
                if hit < rule.after:
                    continue
                if rule.times is not None and self._count[i] >= rule.times:
                    continue
                if rule.probability < 1.0:
                    # Deterministic per-(seed, rule, hit) draw: tuple-of-
                    # int hashing is stable across processes.
                    draw = random.Random(
                        hash((self.seed, i, hit))
                    ).random()
                    if draw >= rule.probability:
                        continue
                self._count[i] += 1
                if len(self.injected) < self.MAX_LOG:
                    self.injected.append(_Fired(site, rule.kind, key))
                return rule
        return None


# -- ambient plan -------------------------------------------------------------

_active: Optional[FaultPlan] = None
_active_lock = threading.Lock()


def install_plan(plan: Optional[FaultPlan]) -> None:
    global _active
    with _active_lock:
        _active = plan


def clear_plan() -> None:
    install_plan(None)


def current_plan() -> Optional[FaultPlan]:
    return _active


@contextlib.contextmanager
def active_plan(plan: Optional[FaultPlan]) -> Iterator[Optional[FaultPlan]]:
    """Scope a plan: install on entry, restore the previous on exit."""
    global _active
    with _active_lock:
        previous = _active
        _active = plan
    try:
        yield plan
    finally:
        with _active_lock:
            _active = previous


def plan_from_env(environ=os.environ) -> Optional[FaultPlan]:
    spec = environ.get(FAULT_PLAN_ENV, "").strip()
    if not spec:
        return None
    return FaultPlan.from_spec(spec)


# -- injection points ---------------------------------------------------------


def _record(site: str, kind: str, key: str) -> None:
    from spark_examples_tpu import obs
    from spark_examples_tpu.obs.tracer import collection_active

    obs.instant("fault_injected", scope="p", site=site, kind=kind, key=key)
    if collection_active():
        obs.get_registry().counter(
            "resilience_faults_injected_total",
            "Faults injected by the active fault plan",
        ).labels(site=site, kind=kind).inc()


def take(
    site: str, key: str = "", plan: Optional[FaultPlan] = None
) -> Optional[FaultRule]:
    """Decide-and-record without acting — for sites whose kinds need
    local handling (torn writes). Returns the fired rule or None."""
    plan = plan if plan is not None else _active
    if plan is None:
        return None
    rule = plan.decide(site, key)
    if rule is not None:
        _record(site, rule.kind, key)
    return rule


def inject(site: str, key: str = "", plan: Optional[FaultPlan] = None) -> None:
    """The standard injection point: no-op without a plan; a fired
    ``stall`` sleeps, anything else raises :class:`InjectedFault`."""
    rule = take(site, key, plan)
    if rule is None:
        return
    if rule.kind == "stall":
        time.sleep(rule.stall_s)
        return
    raise InjectedFault(site, rule.kind, key, rule.message)


def inject_write(
    site: str, path: str, plan: Optional[FaultPlan] = None
) -> None:
    """Write-seam injection point for tmp-then-atomic-rename protocols
    (the mirror's ``mirror.write``): ``torn`` truncates the half-written
    tmp file to half its bytes AND raises — the kill -9-mid-write shape,
    where the commit rename must never run and the partial can only
    ever exist under a ``*.tmp-*`` name; ``stall`` sleeps; anything
    else raises. No-op without a plan. (The checkpoint seams keep
    their own torn shape — truncate-after-commit — because their
    tolerant loaders are the defense under test there.)"""
    rule = take(site, key=os.path.basename(path), plan=plan)
    if rule is None:
        return
    if rule.kind == "torn":
        try:
            size = os.path.getsize(path)
            with open(path, "r+b") as f:
                f.truncate(max(1, size // 2))
        except OSError:
            pass
        raise InjectedFault(
            site, "torn", os.path.basename(path), rule.message
        )
    if rule.kind == "stall":
        time.sleep(rule.stall_s)
        return
    raise InjectedFault(site, rule.kind, os.path.basename(path), rule.message)


def wrap_lines(
    site: str,
    lines: Iterator[bytes],
    key: str = "",
    plan: Optional[FaultPlan] = None,
    truncate_silently: bool = True,
) -> Iterator[bytes]:
    """Apply stream-shaped faults to an iterator of wire lines.

    The decision is taken once, at stream start; the fault acts at the
    rule's ``at_line``: ``truncate`` ends the stream early (the framing
    layer sees no end frame), ``corrupt`` garbles that line (unframed /
    unparseable downstream), ``error`` raises mid-stream, ``stall``
    sleeps once and continues. Streams shorter than ``at_line`` escape
    the fault — keep ``at_line`` small.

    ``truncate_silently`` must reflect what the wrapped transport can
    DETECT: the HTTP tier's end-frame protocol turns a silent early end
    into a loud missing-frame error, so silence is the faithful
    injection there — but a transport with no end sentinel (gRPC, whose
    own framing turns real truncation into a status) must receive the
    fault as a raised error, or the injection would silently drop
    records and corrupt results, which no REAL failure of that
    transport can do.
    """
    plan = plan if plan is not None else _active
    rule = None
    if plan is not None:
        rule = plan.decide(site, key)
        if rule is not None:
            _record(site, rule.kind, key)
    if rule is None:
        yield from lines
        return
    n = 0
    for line in lines:
        if n == rule.at_line:
            if rule.kind == "truncate":
                if truncate_silently:
                    return
                raise InjectedFault(site, "truncate", key, rule.message)
            if rule.kind == "error":
                raise InjectedFault(site, "error", key, rule.message)
            if rule.kind == "stall":
                time.sleep(rule.stall_s)
            elif rule.kind == "corrupt":
                yield b"\x00\xffcorrupt\xff\x00" + line[:8]
                n += 1
                continue
        yield line
        n += 1
