"""Per-endpoint circuit breaker: shed load from a failing tier, probe back.

A retry policy alone makes a down endpoint WORSE: thousands of shard
requests each burning their full attempt budget against a dead server
turns one failure into a retry storm. The breaker is the collective
memory the per-call loops lack — after ``failure_threshold``
consecutive retryable failures against one endpoint it OPENS and every
call sheds instantly (:class:`CircuitOpenError`, an ``IOError`` so
existing transport-failure handling applies), until ``cooldown_s``
elapses and the breaker lets a bounded number of HALF-OPEN probes
through: one success closes the circuit, one failure re-opens it and
re-arms the cooldown.

Only *retryable* (infrastructural) failures feed the breaker — a served
404/401 is the endpoint answering, and must never blow the fuse for
requests that would succeed.

Every transition is emitted to the obs timeline
(``breaker_transition`` instants) and the metrics registry
(``resilience_breaker_transitions_total{endpoint,to}``), so a chaos run
or a production stall shows breaker behavior on the same artifacts the
PR-1 observability layer validates.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict

__all__ = [
    "BreakerSet",
    "CircuitBreaker",
    "CircuitOpenError",
    "CLOSED",
    "OPEN",
    "HALF_OPEN",
]

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"

# One source of truth for the breaker's shape — the config dataclass
# and CLI flags derive their defaults from here.
DEFAULT_FAILURE_THRESHOLD = 8
DEFAULT_COOLDOWN_S = 15.0


class CircuitOpenError(IOError):
    """Raised instead of attempting a call while the circuit is open."""

    def __init__(self, endpoint: str, retry_in: float):
        super().__init__(
            f"circuit open for {endpoint}; next probe in "
            f"{max(0.0, retry_in):.1f}s"
        )
        self.endpoint = endpoint
        self.retry_in = retry_in


def _record_probe(endpoint: str, outcome: str) -> None:
    """Half-open probe observability: without this, shed-vs-probe
    behavior is invisible on the timeline — an operator cannot tell "the
    breaker is probing its way back" from "the breaker is wedged open".
    Outcomes: ``admitted`` (a probe slot granted), ``success`` (the
    probe closed the circuit), ``failure`` (the probe re-opened it),
    ``released`` (slot returned with no verdict — an abandoned
    stream)."""
    from spark_examples_tpu import obs
    from spark_examples_tpu.obs.tracer import collection_active

    obs.instant(
        "breaker_probe", scope="p", endpoint=endpoint, outcome=outcome
    )
    if collection_active():
        obs.get_registry().counter(
            "breaker_probe_total",
            "Half-open circuit-breaker probe outcomes per endpoint",
        ).labels(endpoint=endpoint, outcome=outcome).inc()


def _record_transition(endpoint: str, from_state: str, to_state: str) -> None:
    from spark_examples_tpu import obs
    from spark_examples_tpu.obs.tracer import collection_active

    obs.instant(
        "breaker_transition",
        scope="p",
        endpoint=endpoint,
        **{"from": from_state, "to": to_state},
    )
    if collection_active():
        obs.get_registry().counter(
            "resilience_breaker_transitions_total",
            "Circuit-breaker state transitions per endpoint",
        ).labels(endpoint=endpoint, to=to_state).inc()


class CircuitBreaker:
    """One endpoint's closed/open/half-open state machine (thread-safe)."""

    def __init__(
        self,
        endpoint: str,
        failure_threshold: int = DEFAULT_FAILURE_THRESHOLD,
        cooldown_s: float = DEFAULT_COOLDOWN_S,
        half_open_probes: int = 1,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.endpoint = endpoint
        self.failure_threshold = max(1, failure_threshold)
        self.cooldown_s = cooldown_s
        self.half_open_probes = max(1, half_open_probes)
        self._clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED
        self._failures = 0
        self._opened_at = 0.0
        self._probes_in_flight = 0

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def _transition(self, to_state: str) -> None:
        # Called under self._lock.
        from_state = self._state
        self._state = to_state
        _record_transition(self.endpoint, from_state, to_state)

    def before_call(self) -> None:
        """Gate one call: pass in CLOSED, shed in OPEN (until the
        cooldown converts it to a HALF_OPEN probe window), admit a
        bounded number of probes in HALF_OPEN."""
        with self._lock:
            if self._state == OPEN:
                elapsed = self._clock() - self._opened_at
                if elapsed < self.cooldown_s:
                    raise CircuitOpenError(
                        self.endpoint, self.cooldown_s - elapsed
                    )
                self._transition(HALF_OPEN)
                self._probes_in_flight = 0
            if self._state == HALF_OPEN:
                if self._probes_in_flight >= self.half_open_probes:
                    raise CircuitOpenError(
                        self.endpoint,
                        self.cooldown_s - (self._clock() - self._opened_at),
                    )
                self._probes_in_flight += 1
                _record_probe(self.endpoint, "admitted")

    def record_success(self) -> None:
        """Record transport-level liveness: a returned result OR a
        served application error (the endpoint answered — the retry
        classifiers' non-retryable verdict). Closes a half-open probe."""
        with self._lock:
            if self._state == HALF_OPEN:
                _record_probe(self.endpoint, "success")
                self._transition(CLOSED)
                self._probes_in_flight = 0
            self._failures = 0

    def release_probe(self) -> None:
        """Give back a half-open probe slot with NO verdict — for calls
        that ended without evidence either way (a consumer abandoning a
        stream mid-probe). Without this release, an abandoned probe
        would wedge the breaker half-open forever."""
        with self._lock:
            if self._state == HALF_OPEN and self._probes_in_flight > 0:
                self._probes_in_flight -= 1
                _record_probe(self.endpoint, "released")

    def record_failure(self) -> None:
        """Count one RETRYABLE failure (the classifier's verdict — a
        served 404 must never reach here)."""
        with self._lock:
            if self._state == HALF_OPEN:
                # The probe failed: re-open and re-arm the cooldown.
                _record_probe(self.endpoint, "failure")
                self._transition(OPEN)
                self._opened_at = self._clock()
                self._probes_in_flight = 0
                return
            if self._state == CLOSED:
                self._failures += 1
                if self._failures >= self.failure_threshold:
                    self._transition(OPEN)
                    self._opened_at = self._clock()


class BreakerSet:
    """Lazy per-endpoint breakers sharing one config — a transport's set.

    Keys are endpoint names (the HTTP tier uses paths, the gRPC tier
    method names); each gets its own state machine so a broken
    ``/export-sidecar`` cannot shed ``/variants`` traffic.
    """

    def __init__(
        self,
        prefix: str = "",
        failure_threshold: int = DEFAULT_FAILURE_THRESHOLD,
        cooldown_s: float = DEFAULT_COOLDOWN_S,
        half_open_probes: int = 1,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.prefix = prefix
        self.failure_threshold = failure_threshold
        self.cooldown_s = cooldown_s
        self.half_open_probes = half_open_probes
        self._clock = clock
        self._lock = threading.Lock()
        self._breakers: Dict[str, CircuitBreaker] = {}

    def get(self, endpoint: str) -> CircuitBreaker:
        with self._lock:
            b = self._breakers.get(endpoint)
            if b is None:
                name = (
                    f"{self.prefix}{endpoint}" if self.prefix else endpoint
                )
                b = self._breakers[endpoint] = CircuitBreaker(
                    name,
                    failure_threshold=self.failure_threshold,
                    cooldown_s=self.cooldown_s,
                    half_open_probes=self.half_open_probes,
                    clock=self._clock,
                )
            return b

    def states(self) -> Dict[str, str]:
        with self._lock:
            return {k: b.state for k, b in self._breakers.items()}
