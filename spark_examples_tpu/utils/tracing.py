"""Tracing/profiling: jax.profiler capture + per-stage wall-clock.

The reference has no custom tracing (drivers just set log4j to WARN and
lean on the Spark UI — SURVEY.md §5); the TPU framework does better: an
optional ``jax.profiler`` trace (viewable in TensorBoard/Perfetto) around
any region, plus a lightweight stage timer whose report is the wall-clock
decomposition of a pipeline run.
"""

from __future__ import annotations

import contextlib
import time
from typing import Dict, Iterator, Optional

__all__ = ["StageTimer", "profiler_trace"]


class StageTimer:
    """Accumulates wall-clock per named stage; prints a report block.

    Stages may also attach short diagnostic notes (e.g. the spectral gap
    ratio from the randomized eig) which print alongside the timings —
    the report is the one artifact every run shows the user.
    """

    def __init__(self) -> None:
        self.seconds: Dict[str, float] = {}
        self.notes: Dict[str, list] = {}
        self._active: list = []

    def note(self, text: str) -> None:
        """Attach a note to the currently-running stage.

        Library code deep under a stage (e.g. the eig kernels) need not
        know what the driver named its stages; a note issued outside any
        stage files under "" and still prints, so diagnostics can never
        vanish by landing on an unknown key.
        """
        key = self._active[-1] if self._active else ""
        self.notes.setdefault(key, []).append(text)

    @contextlib.contextmanager
    def stage(self, name: str) -> Iterator[None]:
        t0 = time.perf_counter()
        self._active.append(name)
        try:
            yield
        finally:
            self._active.pop()
            self.seconds[name] = (
                self.seconds.get(name, 0.0) + time.perf_counter() - t0
            )

    def report(self) -> str:
        total = sum(self.seconds.values())
        lines = ["Stage wall-clock", "----------------"]
        for name, secs in self.seconds.items():
            pct = 100.0 * secs / total if total else 0.0
            lines.append(f"{name}: {secs:.3f}s ({pct:.1f}%)")
            lines.extend(f"  {n}" for n in self.notes.get(name, ()))
        lines.extend(f"{n}" for n in self.notes.get("", ()))
        lines.append(f"total: {total:.3f}s")
        return "\n".join(lines)


@contextlib.contextmanager
def profiler_trace(trace_dir: Optional[str]) -> Iterator[None]:
    """``jax.profiler.trace`` when a directory is given, no-op otherwise."""
    if not trace_dir:
        yield
        return
    import jax

    with jax.profiler.trace(trace_dir):
        yield
