"""Tracing/profiling: jax.profiler capture + per-stage wall-clock.

The reference has no custom tracing (drivers just set log4j to WARN and
lean on the Spark UI — SURVEY.md §5); the TPU framework does better: an
optional ``jax.profiler`` trace (viewable in TensorBoard/Perfetto) around
any region, plus a lightweight stage timer whose report is the wall-clock
decomposition of a pipeline run.

Since the unified telemetry layer landed, :class:`StageTimer` is a thin
shim over :mod:`spark_examples_tpu.obs`: every stage also records an
ambient tracer span (so driver stages land on the Chrome-trace timeline
and in the run manifest when ``--trace-out``/``--manifest-out`` are
given) and every note an instant event. The report block — the one
artifact every run prints — is unchanged.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Dict, Iterator, List, Optional

from spark_examples_tpu import obs

__all__ = ["StageTimer", "profiler_trace"]


class StageTimer:
    """Accumulates wall-clock per named stage; prints a report block.

    Stages may also attach short diagnostic notes (e.g. the spectral gap
    ratio from the randomized eig) which print alongside the timings —
    the report is the one artifact every run shows the user.

    Thread-safe: the active-stage stack is **thread-local** (concurrent
    feeder threads each nest their own stages; one thread closing a
    stage can never pop another thread's), and the ``seconds``/``notes``
    accumulation is lock-guarded — the same stage name timed on several
    threads sums correctly.
    """

    def __init__(self) -> None:
        self.seconds: Dict[str, float] = {}
        self.notes: Dict[str, List[str]] = {}
        self._lock = threading.Lock()
        self._local = threading.local()
        # Insertion order of first-finish per stage, for a stable report.
        self._order: List[str] = []

    def _stack(self) -> list:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    def note(self, text: str) -> None:
        """Attach a note to the currently-running stage.

        Library code deep under a stage (e.g. the eig kernels) need not
        know what the driver named its stages; a note issued outside any
        stage files under "" and still prints, so diagnostics can never
        vanish by landing on an unknown key. The note is also mirrored
        onto the trace timeline as an instant event.
        """
        stack = self._stack()
        key = stack[-1] if stack else ""
        with self._lock:
            self.notes.setdefault(key, []).append(text)
        obs.instant("note", stage=key, text=text)

    @contextlib.contextmanager
    def stage(self, name: str) -> Iterator[None]:
        t0 = time.perf_counter()
        self._stack().append(name)
        try:
            with obs.span(name):
                yield
        finally:
            self._stack().pop()
            dt = time.perf_counter() - t0
            with self._lock:
                if name not in self.seconds:
                    self._order.append(name)
                self.seconds[name] = self.seconds.get(name, 0.0) + dt

    def report(self) -> str:
        with self._lock:
            seconds = {k: self.seconds[k] for k in self._order}
            notes = {k: list(v) for k, v in self.notes.items()}
        total = sum(seconds.values())
        lines = ["Stage wall-clock", "----------------"]
        for name, secs in seconds.items():
            pct = 100.0 * secs / total if total else 0.0
            lines.append(f"{name}: {secs:.3f}s ({pct:.1f}%)")
            lines.extend(f"  {n}" for n in notes.get(name, ()))
        lines.extend(f"{n}" for n in notes.get("", ()))
        lines.append(f"total: {total:.3f}s")
        return "\n".join(lines)


@contextlib.contextmanager
def profiler_trace(trace_dir: Optional[str]) -> Iterator[None]:
    """``jax.profiler.trace`` when a directory is given, no-op otherwise."""
    if not trace_dir:
        yield
        return
    import jax

    with jax.profiler.trace(trace_dir):
        yield
