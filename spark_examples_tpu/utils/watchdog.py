"""Fail-stop watchdog for collective phases — the process-loss answer.

A peer process dying mid-run leaves the survivors blocked inside a
gloo/XLA collective that Python cannot interrupt: the wait lives in
native code, so no exception, signal handler, or timeout wrapper in the
caller can reclaim the thread. The sound remedy is fail-stop — detect
the stall, kill THIS process loudly with a distinctive exit code, and
let the operator (or a supervisor) relaunch the job; checkpoint/resume
then recovers every host from the last collective round
(``models/pca.py _checkpointed_pod``).

This is the pod-collective analog of the elasticity the reference got
free from Spark's task re-execution (SURVEY.md §2.10): Spark reschedules
a lost executor's tasks onto survivors; an SPMD pod cannot — every
process runs the same collective program — so recovery is
restart-with-resume, and the watchdog's job is to turn "hang forever"
into "die in ``timeout`` seconds with a clear diagnostic".
"""

from __future__ import annotations

import contextlib
import os
import sys
import threading
from typing import Callable, Dict, Iterator, Optional

__all__ = [
    "CollectiveWatchdog",
    "EXIT_COLLECTIVE_TIMEOUT",
    "register_flush_hook",
    "run_flush_hooks",
    "unregister_flush_hook",
]

# Distinctive code so supervisors can tell "peer lost, relaunch me" from
# ordinary failures (sysexits.h stops at 78; 77 = EX_NOPERM is unused in
# this codebase).
EXIT_COLLECTIVE_TIMEOUT = 77

# Pre-exit flush hooks: durable-state owners (the analysis job journal,
# any open checkpoint lane writer) register a flush here so the
# fail-stop path leaves their state as durable as a clean shutdown —
# the same guarantee telemetry already had via flush_telemetry. Keyed
# by name so an owner can replace/unregister its own hook.
_flush_hooks: Dict[str, Callable[[], None]] = {}
_flush_lock = threading.Lock()


def register_flush_hook(name: str, fn: Callable[[], None]) -> None:
    """Register ``fn`` to run right before a fail-stop ``os._exit``
    (latest registration under a name wins)."""
    with _flush_lock:
        _flush_hooks[name] = fn


def unregister_flush_hook(name: str) -> None:
    with _flush_lock:
        _flush_hooks.pop(name, None)


def run_flush_hooks(deadline_s: float = 5.0) -> None:
    """Run every registered hook, best-effort and BOUNDED — a dying
    process must never fail (or hang) for want of one flush. Hooks run
    on a daemon thread joined with a deadline: a flush wedged in the
    kernel (fsync against a hung mount — the very stall that fired the
    watchdog) must not turn fail-stop into a permanent hang."""
    with _flush_lock:
        hooks = list(_flush_hooks.items())
    if not hooks:
        return

    def run_all() -> None:
        for name, fn in hooks:
            try:
                fn()
            except Exception:  # pragma: no cover - dying anyway
                print(
                    f"WARNING: pre-exit flush hook {name!r} failed",
                    file=sys.stderr,
                )

    t = threading.Thread(target=run_all, daemon=True)
    t.start()
    t.join(deadline_s)
    if t.is_alive():  # pragma: no cover - requires wedged storage
        print(
            f"WARNING: pre-exit flush hooks still running after "
            f"{deadline_s}s; exiting without them.",
            file=sys.stderr,
        )


class CollectiveWatchdog:
    """Arms a hard deadline around each collective phase.

    ``timeout_s`` budgets one whole phase INCLUDING its host-side work
    (a checkpoint round = ingest + collective accumulate + snapshot), so
    set it to a multiple of the expected round time, not of network
    latency. ``None``/0 disables arming entirely (the default: a lone
    process or an interactive run should never be shot by a timer).
    """

    def __init__(self, timeout_s: Optional[float]):
        self.timeout_s = timeout_s

    @contextlib.contextmanager
    def armed(self, what: str) -> Iterator[None]:
        if not self.timeout_s:
            yield
            return
        timer = threading.Timer(self.timeout_s, self._fire, (what,))
        timer.daemon = True
        timer.start()
        try:
            yield
        finally:
            timer.cancel()

    def _fire(self, what: str) -> None:
        print(
            f"FATAL: collective phase '{what}' exceeded "
            f"{self.timeout_s}s — a peer process is likely lost and the "
            "collective will never complete. Exiting "
            f"{EXIT_COLLECTIVE_TIMEOUT}; relaunch the job with the same "
            "manifest and --checkpoint-dir to resume every host from the "
            "last snapshotted round.",
            file=sys.stderr,
            flush=True,
        )
        # Durable state FIRST (job journal, open checkpoint lanes —
        # whatever registered a pre-exit hook), then telemetry: the
        # stall must be ON the trace timeline, not only in stderr, and
        # every flushed file must exist after os._exit.
        run_flush_hooks()
        try:
            from spark_examples_tpu import obs

            obs.instant(
                "collective_watchdog_fired",
                scope="g",
                phase=what,
                timeout_s=self.timeout_s,
            )
            obs.flush_telemetry(reason=f"watchdog fired in '{what}'")
        except Exception:  # pragma: no cover - dying anyway
            pass
        os._exit(EXIT_COLLECTIVE_TIMEOUT)
