"""Fail-stop watchdog for collective phases — the process-loss answer.

A peer process dying mid-run leaves the survivors blocked inside a
gloo/XLA collective that Python cannot interrupt: the wait lives in
native code, so no exception, signal handler, or timeout wrapper in the
caller can reclaim the thread. The sound remedy is fail-stop — detect
the stall, kill THIS process loudly with a distinctive exit code, and
let the operator (or a supervisor) relaunch the job; checkpoint/resume
then recovers every host from the last collective round
(``models/pca.py _checkpointed_pod``).

This is the pod-collective analog of the elasticity the reference got
free from Spark's task re-execution (SURVEY.md §2.10): Spark reschedules
a lost executor's tasks onto survivors; an SPMD pod cannot — every
process runs the same collective program — so recovery is
restart-with-resume, and the watchdog's job is to turn "hang forever"
into "die in ``timeout`` seconds with a clear diagnostic".
"""

from __future__ import annotations

import contextlib
import os
import sys
import threading
from typing import Iterator, Optional

__all__ = ["CollectiveWatchdog", "EXIT_COLLECTIVE_TIMEOUT"]

# Distinctive code so supervisors can tell "peer lost, relaunch me" from
# ordinary failures (sysexits.h stops at 78; 77 = EX_NOPERM is unused in
# this codebase).
EXIT_COLLECTIVE_TIMEOUT = 77


class CollectiveWatchdog:
    """Arms a hard deadline around each collective phase.

    ``timeout_s`` budgets one whole phase INCLUDING its host-side work
    (a checkpoint round = ingest + collective accumulate + snapshot), so
    set it to a multiple of the expected round time, not of network
    latency. ``None``/0 disables arming entirely (the default: a lone
    process or an interactive run should never be shot by a timer).
    """

    def __init__(self, timeout_s: Optional[float]):
        self.timeout_s = timeout_s

    @contextlib.contextmanager
    def armed(self, what: str) -> Iterator[None]:
        if not self.timeout_s:
            yield
            return
        timer = threading.Timer(self.timeout_s, self._fire, (what,))
        timer.daemon = True
        timer.start()
        try:
            yield
        finally:
            timer.cancel()

    def _fire(self, what: str) -> None:
        print(
            f"FATAL: collective phase '{what}' exceeded "
            f"{self.timeout_s}s — a peer process is likely lost and the "
            "collective will never complete. Exiting "
            f"{EXIT_COLLECTIVE_TIMEOUT}; relaunch the job with the same "
            "manifest and --checkpoint-dir to resume every host from the "
            "last snapshotted round.",
            file=sys.stderr,
            flush=True,
        )
        # The stall must be ON the trace timeline, not only in stderr —
        # and the trace file must exist after os._exit, so flush now.
        try:
            from spark_examples_tpu import obs

            obs.instant(
                "collective_watchdog_fired",
                scope="g",
                phase=what,
                timeout_s=self.timeout_s,
            )
            obs.flush_telemetry(reason=f"watchdog fired in '{what}'")
        except Exception:  # pragma: no cover - dying anyway
            pass
        os._exit(EXIT_COLLECTIVE_TIMEOUT)
