"""True device-completion barrier for timing and stage attribution.

``block_until_ready`` is NOT a completion barrier on the axon relay
platform: round-4 measurement had 6.9 TFLOP of chained 4096² matmuls
"complete" in 0.04 ms under ``block_until_ready()`` while a 1-element
readback of the same result took 67 ms — the relay's PjRt client resolves
buffer futures at enqueue, so every round-3 number timed with
``block_until_ready`` measured dispatch, not execution (see
PERFORMANCE.md "Timing honesty"). The only reliable barrier through the
relay is a host readback; :func:`host_sync` reads back ONE element per
array (a jitted slice, so the transfer is 4 bytes, not the array), which
costs one sync roundtrip (~65 ms over the tunnel, microseconds on local
CPU/TPU backends where it is equivalent to a real block_until_ready).
"""

from __future__ import annotations

import numpy as np

__all__ = ["host_sync"]


def host_sync(tree) -> None:
    """Block until every array in ``tree`` has actually finished computing.

    Accepts a single array or any pytree of arrays; non-array leaves are
    ignored. Safe on numpy inputs (no-op reads).
    """
    import jax

    for leaf in jax.tree_util.tree_leaves(tree):
        if hasattr(leaf, "ravel"):
            np.asarray(leaf.ravel()[:1])
