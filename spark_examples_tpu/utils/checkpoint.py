"""Checkpoint/resume: shard-group snapshots of the Gramian accumulator.

The reference's resume story is coarse: ``--input-path`` re-reads a saved
``objectFile`` snapshot of the whole ingest output
(``VariantsCommon.scala:52-55``) — all-or-nothing, at ingest granularity.
Here resume is *incremental*: the shard manifest is deterministic
(:func:`spark_examples_tpu.genomics.shards.manifest_digest`), ingest is
idempotent per shard (STRICT boundaries), and the Gramian is an additive
accumulator — so a snapshot of ``(G, shards_done)`` keyed by the manifest
digest resumes the pipeline mid-ingest, skipping completed shards entirely.

Snapshots are a single ``.npz`` (G plus cursor plus digest in one file —
orbax would add nothing for one dense array) committed with tmp + rename:
one atomic filesystem operation, so a crash can never leave the cursor and
the accumulator disagreeing.

The digest must cover everything that determines G's *content*, not just
the shard manifest: the caller passes a run digest combining the manifest
with the variantset id and filter config (see
``VariantsPcaDriver.get_similarity_matrix_checkpointed``) so a snapshot
from a different dataset or ``--min-allele-frequency`` is never resumed.
"""

from __future__ import annotations

import os
import tempfile
from dataclasses import dataclass
from typing import Optional

import numpy as np

__all__ = ["GramianCheckpoint", "save_snapshot", "load_snapshot"]

_SNAP = "gramian_snapshot.npz"


@dataclass(frozen=True)
class GramianCheckpoint:
    g: np.ndarray
    shards_done: int
    run_digest: str
    n_samples: int


def save_snapshot(
    directory: str,
    g,
    shards_done: int,
    run_digest: str,
) -> None:
    """Persist the accumulator state in one atomic rename."""
    os.makedirs(directory, exist_ok=True)
    g = np.asarray(g)
    fd, tmp = tempfile.mkstemp(dir=directory, suffix=".npz.tmp")
    with os.fdopen(fd, "wb") as f:
        np.savez_compressed(
            f,
            g=g,
            shards_done=np.int64(shards_done),
            run_digest=np.bytes_(run_digest.encode()),
        )
    os.replace(tmp, os.path.join(directory, _SNAP))


def load_snapshot(
    directory: str, run_digest: str, n_samples: int
) -> Optional[GramianCheckpoint]:
    """Load a snapshot if it matches the run digest; stale/absent → None.

    A digest mismatch means the manifest, dataset, or filter config changed
    — the snapshot is silently ignored rather than corrupting the run.
    """
    snap_path = os.path.join(directory, _SNAP)
    if not os.path.exists(snap_path):
        return None
    with np.load(snap_path) as z:
        g = z["g"]
        shards_done = int(z["shards_done"])
        stored_digest = bytes(z["run_digest"]).decode()
    if stored_digest != run_digest or g.shape[0] != n_samples:
        return None
    return GramianCheckpoint(
        g=g,
        shards_done=shards_done,
        run_digest=run_digest,
        n_samples=n_samples,
    )
