"""Checkpoint/resume: shard-group snapshots of the Gramian accumulator.

The reference's resume story is coarse: ``--input-path`` re-reads a saved
``objectFile`` snapshot of the whole ingest output
(``VariantsCommon.scala:52-55``) — all-or-nothing, at ingest granularity.
Here resume is *incremental*: the shard manifest is deterministic
(:func:`spark_examples_tpu.genomics.shards.manifest_digest`), ingest is
idempotent per shard (STRICT boundaries), and the Gramian is an additive
accumulator — so a snapshot of ``(G, shards_done)`` keyed by the manifest
digest resumes the pipeline mid-ingest, skipping completed shards entirely.

Snapshots are a single ``.npz`` (G plus cursor plus digest in one file —
orbax would add nothing for one dense array) committed with tmp + rename:
one atomic filesystem operation, so a crash can never leave the cursor and
the accumulator disagreeing.

The digest must cover everything that determines G's *content*, not just
the shard manifest: the caller passes a run digest combining the manifest
with the variantset id and filter config (see
``VariantsPcaDriver.get_similarity_matrix_checkpointed``) so a snapshot
from a different dataset or ``--min-allele-frequency`` is never resumed.
"""

from __future__ import annotations

import os
import tempfile
from dataclasses import dataclass
from typing import Optional

import numpy as np

__all__ = [
    "GramianCheckpoint",
    "save_snapshot",
    "load_snapshot",
    "save_sharded_snapshot",
    "load_sharded_snapshot",
    "index_key",
]

_SNAP = "gramian_snapshot.npz"
_SHARDED_SNAP = "gramian_sharded_snapshot.npz"


def _warn_unreadable(path: str, exc: BaseException) -> None:
    import sys

    print(
        f"WARNING: unreadable Gramian snapshot {path} "
        f"({type(exc).__name__}: {exc}); discarding — ingest restarts "
        "from the last readable state.",
        file=sys.stderr,
    )
    from spark_examples_tpu import obs

    obs.instant(
        "checkpoint_snapshot_unreadable", scope="p", path=path
    )


@dataclass(frozen=True)
class GramianCheckpoint:
    g: np.ndarray
    shards_done: int
    run_digest: str
    n_samples: int


def _apply_write_fault(site: str, path: str) -> None:
    """Honor a fault-plane rule at a checkpoint write seam.

    ``torn`` truncates the just-committed file to half its bytes —
    simulating a torn write on a filesystem without atomic rename
    (exactly what the tolerant loaders must survive); ``error``/
    ``stall`` act as everywhere else. No-op without an active plan.
    """
    from spark_examples_tpu.resilience import faults

    rule = faults.take(site, key=path)
    if rule is None:
        return
    if rule.kind == "torn":
        try:
            size = os.path.getsize(path)
            with open(path, "r+b") as f:
                f.truncate(max(1, size // 2))
        except OSError:
            pass
        return
    if rule.kind == "stall":
        import time

        time.sleep(rule.stall_s)
        return
    raise faults.InjectedFault(site, rule.kind, path, rule.message)


def save_snapshot(
    directory: str,
    g,
    shards_done: int,
    run_digest: str,
) -> None:
    """Persist the accumulator state in one atomic rename."""
    os.makedirs(directory, exist_ok=True)
    g = np.asarray(g)
    fd, tmp = tempfile.mkstemp(dir=directory, suffix=".npz.tmp")
    with os.fdopen(fd, "wb") as f:
        np.savez_compressed(
            f,
            g=g,
            shards_done=np.int64(shards_done),
            run_digest=np.bytes_(run_digest.encode()),
        )
    path = os.path.join(directory, _SNAP)
    os.replace(tmp, path)
    _apply_write_fault("checkpoint.snapshot_write", path)


def _encode_index(index, shape) -> np.ndarray:
    """Shard index (tuple of slices) → (ndim, 2) [start, stop) array."""
    rows = []
    for sl, dim in zip(index, shape):
        rows.append(
            (
                0 if sl.start is None else int(sl.start),
                dim if sl.stop is None else int(sl.stop),
            )
        )
    return np.asarray(rows, np.int64)


def index_key(index, shape) -> tuple:
    """Hashable normalized form of a shard index, for lookup tables."""
    return tuple(map(tuple, _encode_index(index, shape)))


def save_sharded_snapshot(
    directory: str, g, shards_done: int, run_digest: str
) -> None:
    """Snapshot THIS process's addressable shards of a mesh-sharded G.

    The sample-sharded stress regime cannot gather G (tens of GB at
    100k samples — the point of the layout), so each host persists only
    the tiles it already holds, tagged with their global [start, stop)
    indices. Together the per-host snapshots tile the full G; resume
    re-places each tile via the sharding's own index map, so no host
    ever materializes more than its own share. Same atomic tmp+rename
    contract as :func:`save_snapshot`.
    """
    os.makedirs(directory, exist_ok=True)
    arrays = {
        "shards_done": np.int64(shards_done),
        "run_digest": np.bytes_(run_digest.encode()),
        "n": np.int64(g.shape[0]),
    }
    for i, sh in enumerate(g.addressable_shards):
        arrays[f"data_{i}"] = np.asarray(sh.data)
        arrays[f"index_{i}"] = _encode_index(sh.index, g.shape)
    fd, tmp = tempfile.mkstemp(dir=directory, suffix=".npz.tmp")
    with os.fdopen(fd, "wb") as f:
        np.savez_compressed(f, **arrays)
    os.replace(tmp, os.path.join(directory, _SHARDED_SNAP))


def load_sharded_snapshot(
    directory: str, run_digest: str, n_samples: int
) -> Optional[tuple]:
    """→ ``(shards_done, {index_key: tile})`` or None when stale/absent.

    The caller verifies the stored tile set matches the CURRENT
    sharding's addressable indices before using it (a changed mesh or
    process grid also changes the run digest, but the tile-set check
    keeps the loader safe on its own).
    """
    snap_path = os.path.join(directory, _SHARDED_SNAP)
    if not os.path.exists(snap_path):
        return None
    tiles = {}
    try:
        with np.load(snap_path) as z:
            if (
                bytes(z["run_digest"]).decode() != run_digest
                or int(z["n"]) != n_samples
            ):
                return None
            shards_done = int(z["shards_done"])
            i = 0
            while f"data_{i}" in z:
                tiles[tuple(map(tuple, z[f"index_{i}"]))] = z[f"data_{i}"]
                i += 1
    except Exception as e:  # noqa: BLE001 — any torn-file shape
        _warn_unreadable(snap_path, e)
        return None
    return shards_done, tiles


def load_snapshot(
    directory: str, run_digest: str, n_samples: int
) -> Optional[GramianCheckpoint]:
    """Load a snapshot if it matches the run digest; stale/absent → None.

    A digest mismatch means the manifest, dataset, or filter config changed
    — the snapshot is silently ignored rather than corrupting the run.
    """
    snap_path = os.path.join(directory, _SNAP)
    if not os.path.exists(snap_path):
        return None
    try:
        with np.load(snap_path) as z:
            g = z["g"]
            shards_done = int(z["shards_done"])
            stored_digest = bytes(z["run_digest"]).decode()
    except Exception as e:  # noqa: BLE001 — any torn-file shape
        # The atomic-rename protocol cannot produce a torn snapshot, but
        # a non-atomic filesystem (or a crash inside one) can. Resume
        # must degrade to re-ingesting, never die on its own safety net.
        _warn_unreadable(snap_path, e)
        return None
    if stored_digest != run_digest or g.shape[0] != n_samples:
        return None
    return GramianCheckpoint(
        g=g,
        shards_done=shards_done,
        run_digest=run_digest,
        n_samples=n_samples,
    )
