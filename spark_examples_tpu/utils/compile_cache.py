"""Host-feature-keyed XLA persistent compilation cache directory.

The repo-local ``.jax_cache/`` persists across rounds, but XLA's cache key
does not cover host CPU features: a cache entry compiled on a host with
AVX-512 can be loaded on a host without it and jump into illegal
instructions (XLA warns "could lead to ... SIGILL" on feature mismatch —
observed in the round-2 bench tail after the workdir migrated hosts).

The guard is structural rather than reactive: the cache directory name
embeds a digest of this host's CPU feature set (plus the machine
architecture), so a different host simply gets a different — initially
empty — cache directory instead of one full of incompatible binaries.
Stale sibling directories from other hosts are left in place (another
round on the original host can still reuse them); ``.jax_cache/`` is
gitignored either way.
"""

from __future__ import annotations

import hashlib
import os
import platform

__all__ = [
    "host_feature_key",
    "compilation_cache_dir",
    "enable_persistent_cache",
]


def host_feature_key() -> str:
    """Digest of the CPU feature flags the local XLA backend compiles for."""
    feats = ""
    try:
        with open("/proc/cpuinfo") as fh:
            for line in fh:
                if line.startswith(("flags", "Features")):
                    # One processor's flag set suffices; sort for stability
                    # across kernels that order flags differently.
                    feats = " ".join(sorted(line.split(":", 1)[1].split()))
                    break
    except OSError:
        pass  # non-Linux: fall back to coarse platform identity below
    ident = f"{platform.machine()}|{feats}"
    return hashlib.sha256(ident.encode()).hexdigest()[:12]


def compilation_cache_dir(base: str) -> str:
    """Per-host-feature-set subdirectory of ``base`` (created if missing)."""
    path = os.path.join(base, f"host-{host_feature_key()}")
    os.makedirs(path, exist_ok=True)
    return path


def enable_persistent_cache(base: str) -> str:
    """Point jax at the host-keyed cache under ``base``; → the dir used.

    One call shared by every measurement entry point (bench.py, the TPU
    quick probe, the hardware-gated test suite): first-time compiles
    through the axon tunnel take minutes, and a relay-liveness window may
    be short — no harvest step should spend it recompiling another's
    programs. ``base`` is required and callers anchor it to their OWN
    file location (the checkout) — deriving a default from this module's
    path would point a non-editable install at site-packages.
    """
    import jax

    path = compilation_cache_dir(base)
    jax.config.update("jax_compilation_cache_dir", path)
    return path
