"""Flag system: CLI parity with GenomicsConf/PcaConf plus mesh/TPU flags.

Two-level declarative config mirroring the scallop hierarchy
(``GenomicsConf.scala:31-101``): :class:`GenomicsConfig` carries the common
flags with the reference defaults (1M bases/shard, BRCA1 region, Platinum
Genomes set id); :class:`PcaConfig` adds the PCA-driver extras. Spark-only
knobs (``--num-reduce-partitions``, ``--spark-master``) are accepted for CLI
compatibility but map onto mesh/topology flags.
"""

from __future__ import annotations

import argparse
from dataclasses import dataclass, field
from typing import List, Optional

from spark_examples_tpu.arrays.blocks import DEFAULT_BLOCK_VARIANTS
from spark_examples_tpu.ops.pcoa import (
    DEFAULT_RANDOMIZED_OVERSAMPLE,
    DEFAULT_SKETCH_POWER_ITERS,
)
from spark_examples_tpu.resilience.breaker import (
    DEFAULT_COOLDOWN_S,
    DEFAULT_FAILURE_THRESHOLD,
)
from spark_examples_tpu.resilience.policy import RetryPolicy as _RetryPolicy
from spark_examples_tpu.genomics.shards import (
    BRCA1_REFERENCES,
    DEFAULT_BASES_PER_SHARD,
    SexChromosomeFilter,
    Shard,
    shards_for_all_references,
    shards_for_references,
)

__all__ = [
    "GenomicsConfig",
    "PCA_MODES",
    "PcaConfig",
    "add_analyze_flags",
    "add_genomics_flags",
    "add_pca_flags",
]

# THE --pca-mode registry: the one place the allowed-mode set lives.
# argparse choices, the driver's programmatic validation + its error
# message, the serving JobSpec's per-job override validation, and the
# auto-selection gates all derive from this tuple — adding an engine is
# a one-line change here (a sync test pins every consumer against it).
PCA_MODES = ("auto", "fused", "stream", "sparse", "sketch")

def _csv_list(value: str) -> List[str]:
    """argparse type for comma-separated id lists (empty items dropped,
    so a trailing comma is not a silent empty id)."""
    return [item for item in value.split(",") if item]


# Reference well-known variantset ids (SearchVariantsExample.scala:27-31).
PLATINUM_GENOMES = "3049512673186936334"
THOUSAND_GENOMES_PHASE1 = "10473108253681171589"
THOUSAND_GENOMES_PHASE3 = "4252737135923902652"


@dataclass
class GenomicsConfig:
    bases_per_partition: int = DEFAULT_BASES_PER_SHARD
    client_secrets: Optional[str] = None
    input_path: Optional[str] = None
    num_reduce_partitions: int = 10  # accepted for parity; unused by XLA
    output_path: Optional[str] = None
    references: str = BRCA1_REFERENCES
    variant_set_ids: List[str] = field(
        default_factory=lambda: [PLATINUM_GENOMES]
    )
    # TPU-native additions (replace --spark-master):
    mesh_shape: Optional[str] = None  # e.g. "data:4,model:2"
    block_variants: int = DEFAULT_BLOCK_VARIANTS
    # Resilience layer (spark_examples_tpu.resilience): declarative
    # retry policy for the network tiers (HTTP + gRPC), per-endpoint
    # circuit breaking, and the deterministic fault-injection plane.
    # Defaults derive from the layer itself (RetryPolicy / breaker
    # constants) so dataclass, flags, and direct construction agree.
    rpc_retries: int = _RetryPolicy.max_attempts  # attempts (1 = no retry)
    rpc_retry_deadline: Optional[float] = None  # wall-clock budget (s)
    breaker_threshold: int = DEFAULT_FAILURE_THRESHOLD
    breaker_cooldown: float = DEFAULT_COOLDOWN_S
    grpc_idle_timeout: Optional[float] = 120.0  # per-read stream idle (s)
    fault_plan: Optional[str] = None  # FaultPlan JSON (inline or a path)

    def shards(
        self,
        all_references: bool = False,
        sex_filter: SexChromosomeFilter = SexChromosomeFilter.EXCLUDE_XY,
    ) -> List[Shard]:
        """Partitioner selection — PcaConf.getPartitioner
        (GenomicsConf.scala:92-100)."""
        if all_references:
            return shards_for_all_references(
                sex_filter, self.bases_per_partition
            )
        return shards_for_references(
            self.references, self.bases_per_partition
        )


@dataclass
class PcaConfig(GenomicsConfig):
    all_references: bool = False
    debug_datasets: bool = False
    min_allele_frequency: Optional[float] = None
    num_pc: int = 2
    # Cohort sample restriction: `samples` keeps only the named callset
    # ids (None = all), `exclude_samples` then drops ids. Ingest still
    # extracts in the full callset frame; carriers are remapped/dropped
    # at the window boundary, so the Gramian, finish, and emission are
    # sized by the restricted cohort. This is the per-job cohort axis
    # the serving tier's delta/gang paths ride (docs/OPERATIONS.md
    # §4c); meshless uncheckpointed runs only.
    samples: Optional[List[str]] = None
    exclude_samples: Optional[List[str]] = None
    precise: bool = False  # host-f64 eigendecomposition (driver-side LAPACK analog)
    # PCA pipeline route. "auto" (default) runs the fused single-dispatch
    # finish (centering + CholeskyQR subspace eig + row sums in one
    # program, one packed readback — ops/fused.py) on single-host
    # unsharded runs up to --dense-eigh-limit samples, the
    # streamed/dense route everywhere else, and the SPARSE Gramian
    # accumulation (below) on sample-sharded host-local-mesh runs —
    # the biobank shape; "fused" forces the fused finish (errors on
    # configs it cannot serve: --precise, meshes, multi-process);
    # "stream" forces the pre-round-5 dense/randomized route; "sparse"
    # forces sparse-aware Gramian accumulation (ops/sparse.py): G
    # accumulates by OOB-drop scatter straight from CSR carrier
    # windows — no densify, no bit-pack, work O(Σk²) instead of
    # O(N²·V) — 2-D tile-sharded over the mesh when one is configured,
    # finishing through the sharded randomized eig; "sketch" forces the
    # Gramian-FREE engine (ops/sketch.py): the same CSR windows
    # accumulate an (N, k+p) randomized sketch panel instead of any N×N
    # tile — O(N·(k+p)) memory, TSQR + Nyström finish — the
    # million-sample route (auto selects it only where the N² footprint
    # bound would refuse). The allowed set is the PCA_MODES registry
    # above.
    pca_mode: str = "auto"
    # Gramian-free sketch engine knobs (--pca-mode sketch). Oversample
    # p: the panel carries k+p columns through ops/pcoa.
    # randomized_panel_width — the ONE panel-width policy the exact
    # randomized finish shares. Seed: Ω is drawn from a seeded
    # generator, so a run is bit-reproducible for a fixed seed +
    # topology (NOT bit-identical to the exact path — the documented
    # tolerance contract in ops/sketch.py is the correctness bar).
    # Power iterations: extra full streamed passes with Ω ← orth(Y)
    # between them; 0 keeps the single-pass cold-stream discipline,
    # ≥ 2 tightens coordinates toward the top-k tolerance bars.
    sketch_oversample: int = DEFAULT_RANDOMIZED_OVERSAMPLE
    sketch_seed: int = 0
    sketch_power_iters: int = DEFAULT_SKETCH_POWER_ITERS
    # Dense/sparse switch for the sparse-aware Gramian: a window whose
    # carrier density (nnz / (N·V_blk)) is strictly below this scatters
    # straight from CSR; at or above it, it densifies onto the MXU
    # path. Bit-identical either way (integer-exact both routes); the
    # default is the measured crossover with margin (PERFORMANCE.md
    # decision log).
    sparse_density_threshold: float = 0.02
    # Pod-sparse protocol pipeline depth (process-spanning meshes with
    # --pca-mode sparse): how many window slots the sync thread's
    # header/confirm/carrier exchange runs AHEAD of the device scatter,
    # so exchange latency and payload construction hide behind compute.
    # 0 = inline lockstep (the ablation/debug mode); 2 (double
    # buffering) is right unless exchange latency is extreme.
    pod_pipeline_depth: int = 2
    # Pod-sparse gang coalescing: consecutive scatter-route windows
    # merge into one protocol step until their variant-row total
    # reaches this, so tiny windows amortize one exchange instead of
    # paying per-window latency. 0 disables; G is bit-identical at any
    # setting (integer-exact accumulation).
    pod_coalesce_variants: int = 256
    checkpoint_dir: Optional[str] = None
    checkpoint_every: int = 64  # shards per Gramian snapshot
    # World-size-independent checkpointing (utils/elastic.py): work units
    # over the GLOBAL manifest + self-describing lane snapshots, so resume
    # works on any number of hosts and survivors re-execute a dead host's
    # remaining units — the Spark task re-execution analog.
    elastic_checkpoint: bool = False
    trace_dir: Optional[str] = None  # jax.profiler trace output
    # The 100k-sample stress regime (BASELINE.md config #5): shard the N×N
    # Gramian over the mesh instead of replicating it. None = auto (shard
    # when N exceeds sample_shard_threshold).
    sample_sharded: Optional[bool] = None
    sample_shard_threshold: int = 16384
    # N above which the PCoA eigendecomposition switches from dense eigh
    # to randomized subspace iteration (the sharded-eig path).
    dense_eigh_limit: int = 8192
    # Opt-in adaptive convergence for the randomized eig: stop once every
    # top-k Ritz pair's relative residual ‖C·v − λ·v‖/|λ| drops below
    # this (None = the fixed 30-iteration sweep); eigenvector error is
    # then O(tol/gap). Cuts O(N²) matmuls ~2-3× on sharp spectra — pure
    # chip time at stress N.
    eig_tol: Optional[float] = None
    # Shard-parallel host ingest workers (fused paths): 0 = auto (core
    # count capped at 16 for shard extraction; min(4, cores) for the
    # packed-block builder stage), 1 = serial. Results are bit-identical
    # at any setting — shard extraction preserves manifest order, and
    # the block builders' completion-order output feeds an
    # order-independent integer accumulation.
    ingest_workers: int = 0
    # Device-feed staging depth (arrays/feed.device_prefetch): how many
    # transferred blocks the double-buffered host→device feed keeps
    # ahead of the accumulating matmul. Sharding-aware — applies to the
    # replicated and host-local-mesh feeds alike (the process-spanning
    # pod stream is collective lockstep and has no host-side depth).
    # Must be >= 1; 2 (double buffering) is right unless block build
    # latency is very bursty.
    prefetch_depth: int = 2
    # Shard arrival order into the Gramian accumulator on the CSR-direct
    # ingest tier: "manifest" preserves exact manifest order (head-of-
    # line blocking, byte-identical block packing — the historical
    # behavior); "completion" feeds shards as their fetch+decode
    # completes, so a slow remote shard never stalls the device behind
    # it. G is bit-identical either way (integer-exact accumulation —
    # pinned by test); only block composition and wall-clock change.
    # Checkpointed modes keep manifest order (snapshot digests cut at
    # manifest positions). "auto" (the default) resolves to completion
    # on a cold-stream run (the streaming cold path exists to remove
    # arrival-order barriers) and manifest everywhere else; an EXPLICIT
    # manifest/completion is always honored, cold or warm.
    ingest_order: str = "auto"
    # Spark-style speculative execution for straggler shards: when the
    # head-of-line extraction runs far past the median, a duplicate
    # attempt races it and the winner's (identical) result is used.
    speculative_ingest: bool = False
    # Fail-stop deadline (seconds) per pod collective phase: a lost peer
    # stalls survivors inside a native collective forever; the watchdog
    # turns that into a loud exit-77 + snapshot resume (utils/watchdog.py).
    # None = disabled.
    collective_timeout: Optional[float] = None
    # Per-shard ingest retry (the driver-side resilience tier): each
    # shard extraction is idempotent, so failed shards re-execute up to
    # this many total attempts, every attempt drawing down the per-shard
    # wall-clock budget below. 1 = the historical fail-fast behavior.
    shard_retries: int = 1
    shard_retry_deadline: Optional[float] = None
    # Unified telemetry artifacts (spark_examples_tpu.obs): Chrome-trace
    # span timeline, Prometheus metrics dump (+ .jsonl snapshot), and the
    # machine-readable run manifest. None = telemetry off (zero hot-path
    # cost).
    trace_out: Optional[str] = None
    metrics_out: Optional[str] = None
    manifest_out: Optional[str] = None
    # Reads-pipeline surface (models/pairhmm.py + the reads examples):
    # readset filter for streamed reads (None/"" = every readset the
    # cohort holds) and the PairHMM scoring knobs. Pairs per batched
    # forward dispatch (partial flush tiles pad to a pow2 bucket);
    # consensus-haplotype context bases scored on each side of a read's
    # alignment; phred-scaled gap-open/gap-extend penalties (GATK
    # defaults Q45/Q10). Per-pair results are independent of batching,
    # so pairhmm_batch changes wall-clock only.
    read_group_set_id: Optional[str] = None
    pairhmm_batch: int = 128
    pairhmm_context: int = 8
    pairhmm_gap_open_phred: float = 45.0
    pairhmm_gap_ext_phred: float = 10.0


def add_genomics_flags(p: argparse.ArgumentParser) -> None:
    p.add_argument(
        "--bases-per-partition",
        type=int,
        default=DEFAULT_BASES_PER_SHARD,
        help="Partition each reference using a fixed number of bases",
    )
    p.add_argument(
        "--client-secrets",
        default=None,
        help="Credential JSON for network-source auth (interactive "
        "confirmation required, Client.scala:32-41 semantics): either a "
        "pre-exchanged {'token': ...} or a stored OAuth user credential "
        "(client_id + client_secret + refresh_token, exchanged at "
        "startup via the refresh-token grant); offline sources ignore it",
    )
    p.add_argument(
        "--api-url",
        default=None,
        help="Base URL of a Genomics-compatible HTTP service to ingest "
        "from (see the serve-cohort subcommand)",
    )
    p.add_argument(
        "--cache-dir",
        default=None,
        help="Local directory for mirrored remote cohorts (keyed by the "
        "server's /identity digest): repeat runs against the same served "
        "cohort skip the network and hit the warm sidecar tier",
    )
    p.add_argument(
        "--mirror-mode",
        choices=("full", "light"),
        default="full",
        help="With --cache-dir: 'full' mirrors the whole interchange "
        "cohort (every consumer works offline); 'light' downloads only "
        "callsets + the binary CSR sidecar — at all-autosomes scale a "
        "~2.7 GB npz instead of a ~58 GB JSONL, serving the default "
        "fused pca ingest tiers (record-streaming consumers like "
        "--debug-datasets need 'full')",
    )
    p.add_argument(
        "--cold-stream",
        action=argparse.BooleanOptionalAction,
        default=True,
        help="With --cache-dir on a COLD cohort (no completed mirror): "
        "stream wire frames straight into the fetch->decode->build->put "
        "ingest pipeline — the first Gramian step dispatches while later "
        "shards are still on the wire — and write the mirror through in "
        "the background (atomic per-file; a killed run's partial mirror "
        "is reused by the next cold run). --no-cold-stream restores the "
        "phased cold path (download the whole mirror, then ingest). "
        "Warm runs and checkpointed/mesh contracts are unaffected; G is "
        "bit-identical either way",
    )
    p.add_argument(
        "--input-path",
        default=None,
        help="Path to a cohort snapshot or JSONL cohort directory "
        "(replaces the API source)",
    )
    p.add_argument(
        "--num-reduce-partitions",
        type=int,
        default=10,
        help="Accepted for CLI parity (Spark shuffle knob); unused",
    )
    p.add_argument("--output-path", default=None)
    p.add_argument(
        "--references",
        default=BRCA1_REFERENCES,
        help="Comma separated tuples of reference:start:end",
    )
    p.add_argument(
        "--variant-set-id",
        action="append",
        dest="variant_set_ids",
        default=None,
        help="VariantSet id (repeatable for multi-dataset join/merge)",
    )
    p.add_argument(
        "--mesh-shape",
        default=None,
        help="Device mesh, e.g. 'data:4,model:2' (replaces --spark-master)",
    )
    p.add_argument(
        "--block-variants", type=int, default=DEFAULT_BLOCK_VARIANTS
    )
    p.add_argument(
        "--rpc-retries",
        type=int,
        default=GenomicsConfig.rpc_retries,
        help="Total attempts per network request (HTTP/gRPC): transport "
        "errors and infrastructural statuses (429/502/503/504, "
        "Retry-After honored) retry with jittered exponential backoff; "
        "served application errors never do. 1 disables retries",
    )
    p.add_argument(
        "--rpc-retry-deadline",
        type=float,
        default=None,
        help="Wall-clock budget (seconds) per network request that its "
        "attempts draw down; when it runs dry the last error surfaces "
        "even if attempts remain",
    )
    p.add_argument(
        "--breaker-threshold",
        type=int,
        default=GenomicsConfig.breaker_threshold,
        help="Per-endpoint circuit breaker: consecutive retryable "
        "failures before the circuit OPENS and requests shed instantly "
        "instead of burning their attempt budget against a down tier",
    )
    p.add_argument(
        "--breaker-cooldown",
        type=float,
        default=GenomicsConfig.breaker_cooldown,
        help="Seconds an open circuit sheds before admitting a "
        "half-open probe; the probe's success closes it, failure "
        "re-opens and re-arms the cooldown",
    )
    p.add_argument(
        "--grpc-idle-timeout",
        type=float,
        default=GenomicsConfig.grpc_idle_timeout,
        help="Per-read idle deadline (seconds) on gRPC shard streams: "
        "cancels a stream whose peer is connected but delivering "
        "nothing (the wedged-peer case keepalive cannot catch); an "
        "actively-delivering stream never trips it. 0 disables",
    )
    p.add_argument(
        "--fault-plan",
        default=None,
        help="Activate the deterministic fault-injection plane: a JSON "
        "fault plan, inline ('{\"seed\":1,\"rules\":[...]}') or a path "
        "to a file holding one (env "
        "SPARK_EXAMPLES_TPU_FAULT_PLAN works too); see docs/RESILIENCE.md",
    )


def add_pca_flags(p: argparse.ArgumentParser) -> None:
    add_genomics_flags(p)
    p.add_argument(
        "--all-references",
        action="store_true",
        help="Use all the autosomes (overrides --references)",
    )
    p.add_argument("--debug-datasets", action="store_true")
    p.add_argument("--min-allele-frequency", type=float, default=None)
    p.add_argument("--num-pc", type=int, default=2)
    p.add_argument(
        "--samples",
        type=_csv_list,
        default=None,
        help="Comma-separated callset ids restricting the cohort to "
        "exactly these samples (default: every callset of the "
        "variantsets). Ingest stays full-frame; carriers outside the "
        "cohort drop at the window boundary, so results are identical "
        "to a cohort containing only these samples. Meshless "
        "uncheckpointed runs only",
    )
    p.add_argument(
        "--exclude-samples",
        type=_csv_list,
        default=None,
        help="Comma-separated callset ids dropped from the cohort "
        "(applied after --samples); the ±k cohort-tweak axis the "
        "serving tier's delta index resolves incrementally",
    )
    p.add_argument(
        "--precise",
        action="store_true",
        help="Eigendecompose on host in float64 (Breeze/LAPACK analog)",
    )
    p.add_argument(
        "--checkpoint-dir",
        default=None,
        help="Directory for incremental Gramian snapshots (resume support)",
    )
    p.add_argument("--checkpoint-every", type=int, default=64)
    p.add_argument(
        "--elastic-checkpoint",
        action="store_true",
        help="World-size-independent checkpointing: fixed work units over "
        "the GLOBAL manifest with self-describing lane snapshots, so a "
        "crashed or shrunken cluster resumes on ANY number of hosts and "
        "survivors re-execute a dead host's remaining units (the Spark "
        "task re-execution analog). Multi-host runs need --checkpoint-dir "
        "on a shared filesystem; host-local (DP) accumulation regime only",
    )
    p.add_argument(
        "--ingest-workers",
        type=int,
        default=0,
        help="Threads extracting shards AND building packed genotype "
        "blocks concurrently on the host (fused ingest; 0 = auto — one "
        "per core capped at 16 for extraction, min(4, cores) for the "
        "native block builders; 1 = serial; < 0 rejected). Results are "
        "bit-identical at any setting; only wall-clock changes",
    )
    p.add_argument(
        "--prefetch-depth",
        type=int,
        default=PcaConfig.prefetch_depth,
        help="Blocks the double-buffered host→device feed stages ahead "
        "of the accumulating matmul (default 2; must be >= 1). Applies "
        "to the replicated and host-local-mesh feeds alike; the "
        "process-spanning pod stream is collective lockstep and "
        "ignores it",
    )
    p.add_argument(
        "--ingest-order",
        choices=("auto", "manifest", "completion"),
        default=PcaConfig.ingest_order,
        help="Shard arrival order into the Gramian accumulator on the "
        "CSR-direct ingest tier: 'manifest' preserves exact manifest "
        "order; 'completion' feeds shards as their fetch+decode "
        "completes — the remote binary-frame tier's throughput mode, "
        "where a slow shard never stalls the device; 'auto' (default) "
        "picks completion on cold-stream runs and manifest otherwise. "
        "G is bit-identical either way (integer-exact accumulation); "
        "checkpointed runs always use manifest order",
    )
    p.add_argument(
        "--speculative-ingest",
        action="store_true",
        help="Speculatively re-execute straggler shard extractions "
        "(Spark speculation analog): when the head-of-line shard runs "
        "far past the median completed duration, a duplicate attempt "
        "races it on a spare thread and the first identical result "
        "wins; a failed attempt defers to the survivor. Needs "
        "--ingest-workers > 1 (or auto)",
    )
    p.add_argument(
        "--collective-timeout",
        type=float,
        default=None,
        help="Fail-stop deadline (seconds) per pod collective phase: a "
        "lost peer stalls survivors in a native collective forever; with "
        "this set the process exits 77 instead, and relaunching with the "
        "same --checkpoint-dir resumes every host from the last round. "
        "Pod mode arms each synced round; elastic mode arms only the "
        "final partial-G merge, so there the deadline must budget the "
        "whole-run ingest skew between the fastest and slowest host",
    )
    p.add_argument(
        "--shard-retries",
        type=int,
        default=PcaConfig.shard_retries,
        help="Total attempts per ingested shard (fused/checkpointed "
        "ingest tiers): extraction is idempotent, so a failed shard "
        "re-executes with backoff instead of killing the run — results "
        "are identical, only wall-clock changes. 1 = fail fast "
        "(historical behavior)",
    )
    p.add_argument(
        "--shard-retry-deadline",
        type=float,
        default=None,
        help="Per-shard wall-clock budget (seconds) its retry attempts "
        "draw down",
    )
    p.add_argument(
        "--trace-dir",
        default=None,
        help="Write a jax.profiler trace of the run here",
    )
    p.add_argument(
        "--trace-out",
        default=None,
        help="Write a Chrome-trace-event JSON span timeline here "
        "(open in Perfetto: ui.perfetto.dev; host-side stages, RPC "
        "spans, watchdog/retry instant events)",
    )
    p.add_argument(
        "--metrics-out",
        default=None,
        help="Write a Prometheus text-format metrics dump here "
        "(counters/gauges/latency histograms; a .jsonl machine-readable "
        "snapshot is written alongside)",
    )
    p.add_argument(
        "--manifest-out",
        default=None,
        help="Write the machine-readable run manifest JSON here "
        "(config, device topology, stage timings, counters, histogram "
        "summaries — the per-run artifact BENCH rounds diff)",
    )
    p.add_argument(
        "--sample-sharded",
        action=argparse.BooleanOptionalAction,
        default=None,
        help="Shard the N×N Gramian over the mesh (default: auto above "
        "--sample-shard-threshold; --no-sample-sharded forces the "
        "replicated-G path); the 100k-sample stress regime",
    )
    p.add_argument(
        "--sample-shard-threshold", type=int, default=16384
    )
    p.add_argument(
        "--dense-eigh-limit",
        type=int,
        default=8192,
        help="N above which eigendecomposition uses randomized subspace "
        "iteration instead of dense eigh",
    )
    p.add_argument(
        "--pca-mode",
        choices=PCA_MODES,
        default="auto",
        help="PCA pipeline route: 'auto' (default) runs the fused single-"
        "dispatch finish (centering + subspace eig + row sums in one "
        "device program, one readback) on single-host unsharded runs up "
        "to --dense-eigh-limit samples, and the sparse Gramian on "
        "sample-sharded mesh runs — host-local or process-spanning; "
        "'fused' forces the fused finish; 'stream' forces the "
        "dense-eigh/randomized route; 'sparse' forces sparse-aware "
        "Gramian accumulation straight from CSR carrier windows (no "
        "densify/pack, O(nnz-pairs) work, G tile-sharded over the mesh "
        "— the biobank-scale route; a process-spanning mesh runs the "
        "per-window carrier-allgather protocol: ~d*N*V sparse carrier "
        "integers cross hosts per window instead of dense packed "
        "panels); 'sketch' forces the Gramian-FREE randomized sketch "
        "engine (ops/sketch.py): the same CSR windows accumulate an "
        "(N, k+p) panel — no N×N tile anywhere, O(N*(k+p)) memory, "
        "mesh TSQR + Nystrom finish — the million-sample route, "
        "tolerance-pinned against the exact spectrum (see "
        "--sketch-seed); auto only selects it where the N^2 footprint "
        "bound would refuse",
    )
    p.add_argument(
        "--sketch-oversample",
        type=int,
        default=PcaConfig.sketch_oversample,
        help="Sketch-engine panel oversampling p (--pca-mode sketch): "
        "the streamed panel carries k+p columns (via the shared "
        "randomized_panel_width policy, floor p >= 1 so the spectral-"
        "gap check always has a value past k). Larger p tightens the "
        "approximation at O(N*p) memory and per-window FLOP cost; "
        "p >= N-k makes the Nystrom reconstruction exact to roundoff "
        "(the full-rank tolerance regime)",
    )
    p.add_argument(
        "--sketch-seed",
        type=int,
        default=PcaConfig.sketch_seed,
        help="Seed of the sketch engine's Gaussian test matrix "
        "(--pca-mode sketch): a fixed seed + topology reproduces "
        "coordinates bit-for-bit; different seeds agree within the "
        "documented spectrum tolerance (ops/sketch.py), NOT "
        "bit-identically — the sketch path is approximate by design",
    )
    p.add_argument(
        "--sketch-power-iters",
        type=int,
        default=PcaConfig.sketch_power_iters,
        help="Extra full streamed passes of the sketch engine with "
        "Omega <- orth(Y) between them (--pca-mode sketch): 0 "
        "(default) keeps the one-streamed-pass cold-stream "
        "discipline; >= 2 sharpens coordinates to the top-k "
        "tolerance bars on gapped spectra. Each pass re-streams every "
        "CSR window once",
    )
    p.add_argument(
        "--sparse-density-threshold",
        type=float,
        default=PcaConfig.sparse_density_threshold,
        help="Sparse-Gramian dense/sparse switch: windows with carrier "
        "density strictly below this scatter straight from CSR, at or "
        "above it they densify onto the MXU path; results are "
        "bit-identical either way (integer-exact). On a "
        "process-spanning mesh the route is a per-window GLOBAL "
        "decision synced by the carrier-allgather header — hosts whose "
        "same-step windows land on opposite sides of the threshold "
        "fail together (pin the threshold to 0 or large to force one "
        "route on heterogeneous cohorts)",
    )
    p.add_argument(
        "--pod-pipeline-depth",
        type=int,
        default=PcaConfig.pod_pipeline_depth,
        help="Pod-sparse protocol pipeline depth (process-spanning "
        "meshes, --pca-mode sparse): window slots the host-side "
        "header/confirm/carrier exchange runs ahead of the device "
        "scatter, hiding exchange latency and payload construction "
        "behind compute. 0 = inline lockstep (ablation mode); default "
        "2 (double buffering). G is bit-identical at any depth",
    )
    p.add_argument(
        "--pod-coalesce-variants",
        type=int,
        default=PcaConfig.pod_coalesce_variants,
        help="Pod-sparse gang coalescing target: consecutive "
        "scatter-route windows merge into one protocol step until "
        "their variant-row total reaches this, amortizing one "
        "exchange over many tiny windows (tail windows, small "
        "shards). 0 disables coalescing; G is bit-identical at any "
        "setting",
    )
    p.add_argument(
        "--read-group-set-id",
        default=None,
        help="Readset id filter for reads pipelines (pairhmm, "
        "reads-example): only reads of this read group set stream from "
        "the source; default = every readset in the cohort",
    )
    p.add_argument(
        "--pairhmm-batch",
        type=int,
        default=PcaConfig.pairhmm_batch,
        help="Read x haplotype pairs per batched PairHMM forward "
        "dispatch (pow2-bucketed partial tiles; must be >= 1). Per-pair "
        "log-likelihoods are bit-identical at any setting — batching "
        "changes wall-clock only",
    )
    p.add_argument(
        "--pairhmm-context",
        type=int,
        default=PcaConfig.pairhmm_context,
        help="Consensus-haplotype context bases included on each side "
        "of a read's alignment when scoring it (>= 0); the haplotype "
        "window a read is evaluated against is its span plus this "
        "margin",
    )
    p.add_argument(
        "--pairhmm-gap-open-phred",
        type=float,
        default=PcaConfig.pairhmm_gap_open_phred,
        help="Phred-scaled gap-open penalty of the PairHMM transition "
        "model (GATK default 45, i.e. P(open) ~ 3.2e-5); must be > "
        "10*log10(2) ~= 3.01 — at or below it the match "
        "self-transition 1 - 2*10^(-go/10) is non-positive and every "
        "likelihood would be NaN",
    )
    p.add_argument(
        "--pairhmm-gap-ext-phred",
        type=float,
        default=PcaConfig.pairhmm_gap_ext_phred,
        help="Phred-scaled gap-extension penalty of the PairHMM "
        "transition model (GATK default 10, i.e. P(extend) = 0.1); "
        "must be > 0",
    )
    p.add_argument(
        "--eig-tol",
        type=float,
        default=None,
        help="Eigensolver convergence target |Cv - lv|/|l| per top-k "
        "pair; eigenvector error is then O(tol/gap). On the randomized "
        "(sharded / large-N) path: adaptive early stopping (default: "
        "fixed 30-iteration sweep), cutting device matmuls ~2-3x on "
        "sharp spectra. On the fused path (--pca-mode auto/fused): the "
        "residual check-and-retry bar (default 1e-3). The iteration "
        "count used appears in the stage report",
    )


def add_analyze_flags(p: argparse.ArgumentParser) -> None:
    """The serve-cohort analysis-tier surface (serving/): flag defaults
    derive from the serving layer's own constants — one source of
    truth, like the breaker/retry flags above."""
    from spark_examples_tpu.serving.queue import (
        DEFAULT_QUEUE_DEPTH,
        DEFAULT_TENANT_QUOTA,
    )
    from spark_examples_tpu.serving.tier import DEFAULT_RESULT_CACHE

    p.add_argument(
        "--analyze",
        action="store_true",
        help="Serve the multi-tenant analysis job tier: POST /analyze "
        "submits a cohort spec (dataset, references, AF filter, num_pc) "
        "and GET /jobs/<id> polls it; jobs run PCA against the served "
        "cohort on this host's accelerator with admission control, "
        "per-tenant quotas, result caching, and crash-safe resume "
        "(docs/OPERATIONS.md)",
    )
    p.add_argument(
        "--analyze-workers",
        type=int,
        default=1,
        help="Analysis worker threads executing queued jobs (device "
        "phases serialize on one engine lock regardless; extra workers "
        "only overlap host-side work)",
    )
    p.add_argument(
        "--analyze-queue-depth",
        type=int,
        default=DEFAULT_QUEUE_DEPTH,
        help="Bounded analysis queue depth: submissions beyond it shed "
        "with 429 + Retry-After (derived from the retry policy's "
        "backoff over the consecutive-shed streak) instead of queuing "
        "unboundedly",
    )
    p.add_argument(
        "--analyze-tenant-quota",
        type=int,
        default=DEFAULT_TENANT_QUOTA,
        help="Per-tenant in-flight job quota (queued + running): a "
        "tenant at quota sheds with 429 + Retry-After so one greedy "
        "client cannot starve the others",
    )
    p.add_argument(
        "--analyze-journal-dir",
        default=None,
        help="Directory for the crash-safe analysis job journal (plus "
        "per-job Gramian checkpoints): a killed server restarted with "
        "the same directory replays finished jobs into the result "
        "cache and re-queues in-flight ones deterministically; unset = "
        "in-memory only (a crash forgets every job)",
    )
    p.add_argument(
        "--analyze-cache-size",
        type=int,
        default=DEFAULT_RESULT_CACHE,
        help="Result-cache entries kept (LRU), keyed on the cohort "
        "hash + analysis flags: identical submissions are served "
        "without recomputation, across tenants",
    )
    from spark_examples_tpu.serving.deltas import (
        DEFAULT_DELTA_MAX_SAMPLES,
        DEFAULT_GANG_MAX_SAMPLES,
    )

    p.add_argument(
        "--delta-max-samples",
        type=int,
        default=DEFAULT_DELTA_MAX_SAMPLES,
        help="Incremental serving: a submitted cohort whose sample set "
        "differs from a cached ancestor's by at most this many samples "
        "(same variantsets/references/AF filter) is answered by exact "
        "rank-k corrections to the cached Gramian instead of a "
        "from-scratch re-accumulation — bit-identical results, O(k*N) "
        "touch-up instead of O(N*V) ingest; a checksum guard falls "
        "back to cold on any cache doubt (docs/OPERATIONS.md §4c). "
        "0 disables the delta tier",
    )
    from spark_examples_tpu.serving.replica import (
        DEFAULT_HEARTBEAT_S,
        DEFAULT_LEASE_TTL_S,
    )

    p.add_argument(
        "--store-dir",
        default=None,
        help="Shared durable-store directory for replicated serving: "
        "N serve-cohort replicas pointed at the same directory "
        "coordinate through lease-owned jobs (per-replica journals, a "
        "fenced shared job index, shared Gramian checkpoints and delta "
        "write-through), so killing any replica mid-job lets a "
        "survivor resume it bit-identically (docs/OPERATIONS.md "
        "multi-replica runbook). Unset = single-replica local mode; "
        "an unreachable store degrades to the same, never crashes",
    )
    p.add_argument(
        "--replica-id",
        default=None,
        help="Stable identity of this replica in the shared store "
        "(its lease name and journal subdirectory); default is a "
        "generated host-pid-suffix id. Reusing a dead replica's id "
        "resumes its journal; two LIVE replicas must never share one",
    )
    p.add_argument(
        "--replica-lease-ttl",
        type=float,
        default=DEFAULT_LEASE_TTL_S,
        help="Replica lease time-to-live in seconds (> 0): how stale a "
        "peer's heartbeat must be before survivors declare it dead and "
        "adopt its in-flight jobs. Lower = faster failover, higher = "
        "more tolerance for GC/IO pauses before a live replica is "
        "zombied (its late writes are then fenced, not merged)",
    )
    p.add_argument(
        "--replica-heartbeat",
        type=float,
        default=DEFAULT_HEARTBEAT_S,
        help="Replica lease renewal interval in seconds (0 < value < "
        "ttl; ttl/5 to ttl/3 is a sane band): each renewal re-proves "
        "ownership under the fencing token and recovers the store "
        "after degraded spells",
    )
    p.add_argument(
        "--gang-max-samples",
        type=int,
        default=DEFAULT_GANG_MAX_SAMPLES,
        help="Gang batching: queued compatible jobs (same resolved "
        "variantsets/references/AF filter, cohort size at most this) "
        "coalesce into ONE batched Gramian dispatch — cohorts stacked "
        "on a leading batch axis through a vmapped accumulator, one "
        "jit cache entry, per-job results unstacked and journaled "
        "individually (crash-safe replay semantics unchanged; results "
        "bit-identical to serial execution). 0 disables gang batching",
    )


def _config_from_args(cls, args: argparse.Namespace):
    kwargs = {}
    for f in cls.__dataclass_fields__:
        if hasattr(args, f):
            val = getattr(args, f)
            if val is not None or f not in ("variant_set_ids",):
                kwargs[f] = val
    if kwargs.get("variant_set_ids") is None:
        kwargs.pop("variant_set_ids", None)
    return cls(**kwargs)


def genomics_config_from_args(args) -> GenomicsConfig:
    return _config_from_args(GenomicsConfig, args)


def pca_config_from_args(args) -> PcaConfig:
    return _config_from_args(PcaConfig, args)
