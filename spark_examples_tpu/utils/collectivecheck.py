"""Runtime backstop for SPMD collective congruence (GL010's dynamic twin).

GL010 proves at review time that no lockstep collective sits under a
branch on host-local state — for in-tree call sites. A static gate
cannot see version-skewed pods (hosts running different code deriving
different geometry from the same gathered headers), monkeypatched
tests, or an embedder driving the protocol directly. With
``SPARK_EXAMPLES_TPU_COLLECTIVE_CHECK=1`` every pod protocol step
digests its derived (op, geometry) tuple sequence — the route, the
padded row count, the agreed carrier bucket or dense panel width, the
payload dtype — and cross-checks peers over the existing podstream
exchange (one extra tiny frame per step, nothing on the disabled path).
A divergent step raises on EVERY process together, naming the step and
the per-process digests, instead of desyncing the frame protocol or
deadlocking a device collective minutes later.

Enablement is itself agreed: each process advertises its check flag in
the step header, and the digest exchange runs only when every live
process enabled it — a mixed pod degrades to unchecked rather than
desyncing on unexpected frames (the predicate derives from gathered
data, exactly the discipline GL010 codifies).

Disabled (the default) this is one env read per protocol step — host
work on a path already dominated by socket IO.
"""

from __future__ import annotations

import hashlib
import os
from typing import Sequence, Tuple

__all__ = [
    "COLLECTIVE_CHECK_ENV",
    "collective_check_enabled",
    "note_collective_check",
    "step_digest",
    "verify_step_digests",
]

COLLECTIVE_CHECK_ENV = "SPARK_EXAMPLES_TPU_COLLECTIVE_CHECK"

# (op name, geometry ints) pairs — one per lockstep operation of the
# step, in issue order.
OpGeometry = Tuple[str, Tuple[int, ...]]


def collective_check_enabled() -> bool:
    """Read per call (not cached): test fixtures toggle the env var
    around individual suites."""
    return os.environ.get(COLLECTIVE_CHECK_ENV, "") not in ("", "0")


def step_digest(stream: int, step: int, ops: Sequence[OpGeometry]) -> int:
    """Order-sensitive 63-bit digest of one protocol step's (op,
    geometry) sequence. Non-negative always — the exchange reserves
    negative values for 'check disabled on this process'."""
    h = hashlib.blake2b(digest_size=8)
    h.update(f"{stream}|{step}".encode())
    for op, geometry in ops:
        h.update(b"\x00" + op.encode())
        for g in geometry:
            h.update(b"\x01" + str(int(g)).encode())
    return int.from_bytes(h.digest(), "little") & (2**63 - 1)


def note_collective_check(outcome: str) -> None:
    """Count one cross-checked protocol step: ``agree`` (digests
    matched on every live process) or ``divergence`` (mismatch — the
    step raised everywhere). One registration site (GL003); the label
    set rides ``validate_trace._LABELED_COUNTERS``."""
    from spark_examples_tpu import obs

    obs.get_registry().counter(
        "collective_check_steps_total",
        "Pod protocol steps cross-checked by the collective-congruence "
        "runtime backstop, by outcome",
    ).labels(outcome=outcome).inc()


def verify_step_digests(
    step: int, digests: Sequence[int], local_digest: int
) -> None:
    """Compare the gathered per-process digests for one step.

    ``digests`` is the (world,)-length gathered vector — every entry is
    a non-negative digest (the caller only runs the exchange when every
    live process enabled the check). Raises ``RuntimeError`` on
    mismatch — from identical gathered data, so every process raises
    together at the same step.
    """
    distinct = sorted({int(d) for d in digests})
    if len(distinct) <= 1:
        note_collective_check("agree")
        return
    note_collective_check("divergence")
    per_proc = {i: int(d) for i, d in enumerate(digests)}
    raise RuntimeError(
        f"collective-congruence check failed at protocol step {step}: "
        f"per-process (op, geometry) digests diverged {per_proc} "
        f"(local {int(local_digest)}) — the pod is issuing different "
        "collective sequences (version skew, or a geometry derivation "
        "bug); raising on every process together"
    )
