"""Cross-cutting utilities: IO stats, config/flags, checkpointing, logging."""
