"""Ingest observability — the accumulator system.

Parity with ``VariantsRddStats`` (VariantsRDD.scala:160-180): six named
counters fed by the data plane and pretty-printed as a block at job end
(``VariantsCommon.scala:68-73``). Spark merges executor-side accumulators on
the driver; here counters are per-process (threads share them via atomic
increments under the GIL) and multi-host totals are merged with an explicit
all-reduce of the counter vector — see
:func:`spark_examples_tpu.parallel.distributed.allreduce_host_stats`.

Registry backing: every live ``IoStats`` instance is also visible to the
telemetry metrics registry (:mod:`spark_examples_tpu.obs.metrics`) as
``genomics_io_<counter>_total`` — summed over instances by a collector
evaluated at *scrape/manifest* time, not on the hot path. ``add`` runs
once per ingested record (millions per run), so the counters stay plain
per-instance ints here and the registry reads them when someone actually
asks; the ``report()`` block the parity tests pin is byte-identical to
the reference's.
"""

from __future__ import annotations

import threading
import weakref
from dataclasses import dataclass, field

__all__ = ["IoStats", "COUNTER_FIELDS"]

# The accumulator fields, in as_vector()/report() order.
COUNTER_FIELDS = (
    "partitions",
    "reference_bases",
    "requests",
    "unsuccessful_responses",
    "io_exceptions",
    "variants_read",
    "reads_read",
)

# Live instances for the registry collector (weak: a dropped source's
# stats must not leak). A dying instance retires its final counts into
# ``_retired`` from ``__del__`` — a source GC'd before the end-of-run
# manifest flush (the common CLI shape: the driver drops its source
# before the telemetry session exits) still contributes its records.
_instances: "weakref.WeakSet[IoStats]" = weakref.WeakSet()
_retired = dict.fromkeys(COUNTER_FIELDS, 0)
_retired_lock = threading.Lock()


def _collect_io_stats():
    """Registry collector: counters summed over live + retired IoStats.

    NOTE: the sum is a *process-wide cumulative diagnostic view* — a
    merged copy (``allreduce_host_stats`` on a multi-host run, or an
    explicit ``merge``) is itself an instance, so merged totals can
    double-count here; per-instance accounting (the ``report()`` block)
    remains the parity-exact surface.
    """
    with _retired_lock:
        totals = dict(_retired)
    for inst in list(_instances):
        for name in COUNTER_FIELDS:
            totals[name] += getattr(inst, name)
    for name in COUNTER_FIELDS:
        yield (
            f"genomics_io_{name}_total",
            "counter",
            f"IoStats accumulator '{name}' summed over sources "
            "(VariantsRDD.scala:160-180 parity counters)",
            {},
            float(totals[name]),
        )


def _register_collector() -> None:
    from spark_examples_tpu.obs.metrics import register_collector

    register_collector(_collect_io_stats)


_register_collector()


# eq=False keeps the default identity hash: instances live in the
# collector's WeakSet (a generated __eq__ would set __hash__ = None).
# Nothing compared IoStats by value — counts are read field-wise.
@dataclass(eq=False)
class IoStats:
    partitions: int = 0
    reference_bases: int = 0
    requests: int = 0
    unsuccessful_responses: int = 0
    io_exceptions: int = 0
    variants_read: int = 0
    reads_read: int = 0
    _lock: threading.Lock = field(
        default_factory=threading.Lock, repr=False, compare=False
    )

    def __post_init__(self) -> None:
        _instances.add(self)

    @classmethod
    def untracked(cls) -> "IoStats":
        """An instance INVISIBLE to the registry collector — for merged
        views (``allreduce_host_stats``, explicit ``merge`` targets)
        whose counts are copies of already-tracked instances; tracking
        them would double-count the manifest's ``genomics_io_*_total``
        on exactly the multi-host runs telemetry targets."""
        inst = cls()
        _instances.discard(inst)
        inst._untracked = True
        return inst

    def __del__(self) -> None:
        try:
            if getattr(self, "_untracked", False):
                return
            with _retired_lock:
                for name in COUNTER_FIELDS:
                    _retired[name] += getattr(self, name)
        except Exception:  # pragma: no cover - interpreter shutdown
            pass

    def add(self, **deltas: int) -> None:
        with self._lock:
            for name, d in deltas.items():
                setattr(self, name, getattr(self, name) + d)

    def merge(self, other: "IoStats") -> None:
        self.add(**{f: getattr(other, f) for f in COUNTER_FIELDS})

    def as_vector(self):
        """Counter vector for device-side psum merging across hosts."""
        return [getattr(self, f) for f in COUNTER_FIELDS]

    def report(self) -> str:
        """The formatted block of VariantsRDD.scala:168-180."""
        return (
            "Variants API stats\n"
            "------------------\n"
            f"# of partitions: {self.partitions}\n"
            f"# of reference bases requested: {self.reference_bases}\n"
            f"# of API requests: {self.requests}\n"
            f"# of unsuccessful responses: {self.unsuccessful_responses}\n"
            f"# of IO exceptions: {self.io_exceptions}\n"
            f"# of variants read: {self.variants_read}\n"
            f"# of reads read: {self.reads_read}\n"
        )
