"""Ingest observability — the accumulator system.

Parity with ``VariantsRddStats`` (VariantsRDD.scala:160-180): six named
counters fed by the data plane and pretty-printed as a block at job end
(``VariantsCommon.scala:68-73``). Spark merges executor-side accumulators on
the driver; here counters are per-process (threads share them via atomic
increments under the GIL) and multi-host totals are merged with an explicit
all-reduce of the counter vector — see
:func:`spark_examples_tpu.parallel.distributed.allreduce_host_stats`.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field

__all__ = ["IoStats"]


@dataclass
class IoStats:
    partitions: int = 0
    reference_bases: int = 0
    requests: int = 0
    unsuccessful_responses: int = 0
    io_exceptions: int = 0
    variants_read: int = 0
    reads_read: int = 0
    _lock: threading.Lock = field(
        default_factory=threading.Lock, repr=False, compare=False
    )

    def add(self, **deltas: int) -> None:
        with self._lock:
            for name, d in deltas.items():
                setattr(self, name, getattr(self, name) + d)

    def merge(self, other: "IoStats") -> None:
        self.add(
            partitions=other.partitions,
            reference_bases=other.reference_bases,
            requests=other.requests,
            unsuccessful_responses=other.unsuccessful_responses,
            io_exceptions=other.io_exceptions,
            variants_read=other.variants_read,
            reads_read=other.reads_read,
        )

    def as_vector(self):
        """Counter vector for device-side psum merging across hosts."""
        return [
            self.partitions,
            self.reference_bases,
            self.requests,
            self.unsuccessful_responses,
            self.io_exceptions,
            self.variants_read,
            self.reads_read,
        ]

    def report(self) -> str:
        """The formatted block of VariantsRDD.scala:168-180."""
        return (
            "Variants API stats\n"
            "------------------\n"
            f"# of partitions: {self.partitions}\n"
            f"# of reference bases requested: {self.reference_bases}\n"
            f"# of API requests: {self.requests}\n"
            f"# of unsuccessful responses: {self.unsuccessful_responses}\n"
            f"# of IO exceptions: {self.io_exceptions}\n"
            f"# of variants read: {self.variants_read}\n"
            f"# of reads read: {self.reads_read}\n"
        )
