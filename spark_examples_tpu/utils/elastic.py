"""Elastic checkpointing: world-size-independent work units and lanes.

The Spark-task-re-execution analog. Spark reschedules a lost executor's
tasks onto survivors for free; the reference leans on that entirely and
itself only *counts* failures (``VariantsRDD.scala:163-165``, SURVEY.md
§2.10 elasticity row). The non-elastic checkpoint modes here key their
snapshots to the process grid (``host=p/P`` digests over per-host manifest
slices), so recovery demands a relaunch with the SAME world size — a dead
host freezes its share of the work. Elastic mode removes the coupling:

- The **global** manifest is cut into fixed work units of
  ``checkpoint_every`` shards — the analog of a Spark task. Unit
  boundaries depend only on the manifest and the round width, never on
  how many processes exist.
- Each process accumulates its units into a **lane**: one ``.npz``
  holding a partial Gramian plus the exact unit-id set it covers. A lane
  is self-describing — any reader knows precisely what work it holds.
- Resume (at ANY world size): list the shared checkpoint dir, drop lanes
  whose unit set is contained in another lane's (the merge protocol's
  only crash residue — see below), deterministically claim surviving
  lanes round-robin, and re-slice the units no lane covers over the
  CURRENT processes. A dead host's unfinished share is thereby
  re-executed by survivors: Spark's elasticity without a cluster manager.

Crash-safety protocol: a process merges its claimed lanes plus each newly
finished unit into a NEW lane file (atomic tmp+rename), and only then
deletes the lanes the new file supersedes. A crash at any instant leaves
either the old lanes intact, or the merged lane alongside stale subset
lanes — never a torn file, never a unit counted twice after the
subset-discard pass. Lanes never partially overlap under this protocol;
if one ever does (external corruption), it is discarded loudly.

Multi-host elastic mode requires the checkpoint dir to be on a filesystem
all hosts share (the driver verifies the view cross-host before work
begins). This mirrors Spark, whose recovery also runs through shared
state (the driver's lineage + a shared shuffle/storage layer).
"""

from __future__ import annotations

import os
import sys
import tempfile
import uuid
from dataclasses import dataclass
from typing import FrozenSet, List, Optional, Sequence, Tuple
from zipfile import BadZipFile

import numpy as np

__all__ = [
    "Lane",
    "unit_ranges",
    "save_lane",
    "load_lanes",
    "merge_and_supersede",
]

_LANE_PREFIX = "lane-"
_LANE_SUFFIX = ".npz"


@dataclass(frozen=True)
class Lane:
    """One lane's metadata. The Gramian payload is NOT held here: at the
    stress scale (100k samples) one lane's float32 G is ~40 GB, and every
    host lists ALL lanes but needs the payload of only its claimed ones —
    so listing loads unit sets and payloads load on demand."""

    path: str
    units: FrozenSet[int]

    def load_g(self) -> np.ndarray:
        with np.load(self.path) as z:
            return z["g"]


def unit_ranges(n_shards: int, every: int) -> List[Tuple[int, int]]:
    """Global manifest → work-unit shard ranges ``[start, stop)``.

    Pure function of (manifest length, round width): the same units exist
    no matter how many processes compute them — the property that makes
    resume world-size independent.
    """
    every = max(1, every)
    return [
        (lo, min(lo + every, n_shards)) for lo in range(0, n_shards, every)
    ]


def unit_ranges_contig_aligned(shards, every: int) -> List[Tuple[int, int]]:
    """Work units that never split a contig's manifest run.

    Multi-dataset identity joins keep per-contig state (the variant
    identity hashes contig+position+alleles, so matches can only occur
    within one contig): cutting work units at contig boundaries makes an
    incrementally-checkpointed join EXACT — each unit's joined rows equal
    the same contigs' rows in an uninterrupted run. Consecutive whole
    runs pack into units of at most ``every`` shards; a single contig
    longer than ``every`` becomes one oversized unit (it cannot be split
    without breaking join-state locality).

    Precondition (caller-verified): each contig appears as ONE contiguous
    run in the manifest.
    """
    every = max(1, every)
    runs: List[Tuple[int, int]] = []
    lo = 0
    for i in range(1, len(shards) + 1):
        if i == len(shards) or shards[i].contig != shards[lo].contig:
            runs.append((lo, i))
            lo = i
    units: List[Tuple[int, int]] = []
    cur: Optional[List[int]] = None
    for lo, hi in runs:
        if cur is None:
            cur = [lo, hi]
        elif hi - cur[0] <= every:
            cur[1] = hi
        else:
            units.append((cur[0], cur[1]))
            cur = [lo, hi]
    if cur is not None:
        units.append((cur[0], cur[1]))
    return units


def save_lane(
    directory: str,
    g,
    units: Sequence[int],
    run_digest: str,
) -> str:
    """Write one lane atomically (tmp + rename); returns its path."""
    os.makedirs(directory, exist_ok=True)
    g = np.asarray(g)
    fd, tmp = tempfile.mkstemp(dir=directory, suffix=".npz.tmp")
    with os.fdopen(fd, "wb") as f:
        # g_shape is stored separately so readers can validate a lane
        # without decompressing the (N, N) payload member.
        np.savez_compressed(
            f,
            g=g,
            g_shape=np.asarray(g.shape, np.int64),
            units=np.asarray(sorted(units), np.int64),
            run_digest=np.bytes_(run_digest.encode()),
        )
    path = os.path.join(
        directory, f"{_LANE_PREFIX}{uuid.uuid4().hex}{_LANE_SUFFIX}"
    )
    os.replace(tmp, path)
    # Chaos seam: a "torn" rule truncates the committed lane (the
    # non-atomic-filesystem failure load_lanes discards with a warning
    # and whose units resume re-executes) — see utils/checkpoint.py.
    from spark_examples_tpu.utils.checkpoint import _apply_write_fault

    _apply_write_fault("checkpoint.lane_write", path)
    return path


def _read_lane(path: str, run_digest: str, n: int) -> Optional[Lane]:
    try:
        # npz members decompress individually — digest/units/shape checks
        # never pull the (N, N) payload into memory. Lanes written before
        # g_shape existed lack the member; fall back to decompressing the
        # payload once rather than discarding a prior run's progress.
        with np.load(path) as z:
            if bytes(z["run_digest"]).decode() != run_digest:
                return None
            shape = (
                tuple(z["g_shape"]) if "g_shape" in z else z["g"].shape
            )
            if shape != (n, n):
                return None
            return Lane(
                path=path,
                units=frozenset(int(u) for u in z["units"]),
            )
    except (OSError, KeyError, ValueError, BadZipFile):
        # A torn write cannot exist (atomic rename), but an unreadable
        # file from any other source must not kill resume — its work is
        # simply re-executed.
        print(
            f"WARNING: unreadable elastic lane {path}; ignoring.",
            file=sys.stderr,
        )
        from spark_examples_tpu import obs

        obs.instant("elastic_unreadable_lane", scope="p", path=path)
        return None


def load_lanes(directory: str, run_digest: str, n: int) -> List[Lane]:
    """All usable lanes, deterministically de-overlapped.

    Candidates sort by descending unit-count then name, so a merged
    superset lane always wins over the stale subsets it replaced; a lane
    overlapping the kept set in any *partial* way cannot arise from the
    merge protocol and is discarded with a warning. The result is a list
    of pairwise-disjoint lanes, identical on every host that sees the
    same directory.
    """
    if not os.path.isdir(directory):
        return []
    candidates = []
    for name in sorted(os.listdir(directory)):
        if not (name.startswith(_LANE_PREFIX) and name.endswith(_LANE_SUFFIX)):
            continue
        lane = _read_lane(os.path.join(directory, name), run_digest, n)
        if lane is not None:
            candidates.append(lane)
    candidates.sort(key=lambda l: (-len(l.units), os.path.basename(l.path)))
    kept: List[Lane] = []
    covered: set = set()
    for lane in candidates:
        if lane.units.isdisjoint(covered):
            kept.append(lane)
            covered |= lane.units
        elif lane.units <= covered:
            continue  # stale subset left by a crash inside a merge
        else:
            print(
                f"WARNING: elastic lane {lane.path} partially overlaps "
                "other lanes (corruption?); discarding it — its units "
                "will be re-executed.",
                file=sys.stderr,
            )
            from spark_examples_tpu import obs

            obs.instant(
                "elastic_lane_discarded",
                scope="p",
                path=lane.path,
                reason="partial_overlap",
                units_reexecuted=len(lane.units),
            )
    return kept


def merge_and_supersede(
    directory: str,
    g,
    units: Sequence[int],
    run_digest: str,
    supersedes: Sequence[str],
) -> str:
    """Atomically publish a merged lane, then delete the lanes it replaces.

    Write-new-then-delete-old ordering is the crash-safety invariant: the
    merged lane's unit set is a superset of every superseded lane's, so a
    crash between the two steps leaves only subset lanes for
    :func:`load_lanes` to discard.
    """
    path = save_lane(directory, g, units, run_digest)
    from spark_examples_tpu.resilience import faults

    if faults.take("checkpoint.lane_supersede", key=path) is not None:
        # Injected crash in the window between write-new and delete-old:
        # the stale subset lanes stay behind, exactly the residue the
        # load_lanes subset-discard pass exists to clean up.
        return path
    for old in supersedes:
        if os.path.abspath(old) == os.path.abspath(path):
            continue
        try:
            os.remove(old)
        except OSError:
            pass  # already gone — deletion is best-effort cleanup
    return path


def prune_stale_lanes(
    directory: str,
    run_digest: str,
    kept: Sequence[Lane],
    tmp_ttl_seconds: float = 3600.0,
) -> int:
    """Delete lane files that are provably worthless for this run.

    Every parameter change (AF filter, round width, manifest) mints a new
    digest and orphans the previous run's lanes — one compressed (N, N)
    Gramian each, so an un-pruned checkpoint dir grows without bound.
    Removed: lanes that read cleanly but carry a different digest, lanes
    whose unit set the kept lanes already cover (merge-crash residue),
    and ``.npz.tmp`` orphans from a save that was killed mid-write —
    age-gated by ``tmp_ttl_seconds`` so a peer's save actively in flight
    on the shared dir is never yanked out from under it. Unreadable
    ``lane-*.npz`` files are deliberately LEFT in place — they are
    evidence of corruption, and deleting them would hide it. Returns the
    number of files removed.
    """
    import time

    kept_paths = {os.path.abspath(lane.path) for lane in kept}
    covered: set = set()
    for lane in kept:
        covered |= lane.units
    removed = 0
    if not os.path.isdir(directory):
        return 0
    now = time.time()
    for name in sorted(os.listdir(directory)):
        if name.endswith(".npz.tmp"):
            path = os.path.join(directory, name)
            try:
                if now - os.path.getmtime(path) > tmp_ttl_seconds:
                    os.remove(path)
                    removed += 1
            except OSError:
                pass
            continue
        if not (name.startswith(_LANE_PREFIX) and name.endswith(_LANE_SUFFIX)):
            continue
        path = os.path.join(directory, name)
        if os.path.abspath(path) in kept_paths:
            continue
        try:
            with np.load(path) as z:
                digest = bytes(z["run_digest"]).decode()
                units = frozenset(int(u) for u in z["units"])
        except (OSError, KeyError, ValueError, BadZipFile):
            continue  # unreadable: keep as corruption evidence
        if digest != run_digest or units <= covered:
            try:
                os.remove(path)
                removed += 1
            except OSError:
                pass
    return removed


def lane_view_fingerprint(lanes: Sequence[Lane]) -> str:
    """Order-independent digest of (lane name, unit set) pairs.

    Multi-host elastic resume requires every process to see the SAME
    lanes (shared checkpoint dir); the driver allgathers this fingerprint
    and refuses to proceed on divergence, turning a mis-mounted
    checkpoint dir into a loud error instead of a wrong Gramian.
    """
    import hashlib

    h = hashlib.sha256()
    for lane in sorted(lanes, key=lambda l: os.path.basename(l.path)):
        h.update(os.path.basename(lane.path).encode())
        h.update(b":")
        h.update(",".join(map(str, sorted(lane.units))).encode())
        h.update(b";")
    return h.hexdigest()
