"""Axon TPU relay detection — shared by every entry point that must not
hang on a dead tunnel.

The axon sitecustomize registers the TPU plugin before user code runs and
bakes the platform in, so ``JAX_PLATFORMS`` alone is NOT a reliable
signal; presence of the site dir (or an explicit axon platform setting)
is. When the relay is dead, backend init blocks forever dialing it —
``import jax`` itself is safe, which is why a ``jax.config`` override
after import works (see NOTES.md hardware incidents).
"""

from __future__ import annotations

import os

__all__ = ["axon_possible", "relay_alive", "cpu_failover_if_dead"]

RELAY_ADDR = ("127.0.0.1", 8093)
AXON_SITE = "/root/.axon_site"


def axon_possible() -> bool:
    """Could the axon plugin steer this process?"""
    return os.path.isdir(AXON_SITE) or (
        os.environ.get("JAX_PLATFORMS", "") == "axon"
    )


def relay_alive(timeout: float = 5.0) -> bool:
    import socket

    try:
        socket.create_connection(RELAY_ADDR, timeout=timeout).close()
        return True
    except OSError:
        return False


def cpu_failover_if_dead() -> bool:
    """Force the CPU backend when the relay is dead; True if engaged.

    No-op on machines without the axon site (they keep their native
    backends) and when the platform is already explicitly cpu.
    """
    if not axon_possible():
        return False
    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        return False
    if relay_alive():
        return False
    import jax

    jax.config.update("jax_platforms", "cpu")
    return True
