"""Ordered bounded-lookahead parallel map — shard-parallel host ingest.

The cold all-autosomes run is HOST-bound: the device Gramian is
sub-second per chr20 while per-shard extraction (sidecar slice + remap,
JSON parse fallback, or an HTTP round-trip per shard) runs serially.
This is the composition round 2 left open (NOTES round-3 agenda #3):
N workers extract shards concurrently while the consumer — the single
device accumulator — receives results in EXACT manifest order, so the
block packing and every float accumulation order is bit-identical to
the serial path; parallelism changes wall-clock, never results.

The reference gets the same shape from Spark: one task per shard, each
holding its own gRPC stream, reduced into one Gramian
(VariantsRDD.scala:205-235). Threads (not processes) because the heavy
steps release the GIL (numpy slicing/remap, socket IO) and the extracted
call lists flow to the accumulator without serialization.
"""

from __future__ import annotations

import statistics
import time
from typing import Callable, Iterable, Iterator, Optional, Sequence, TypeVar

T = TypeVar("T")
R = TypeVar("R")

__all__ = ["ordered_parallel_map", "completion_parallel_map"]

# Spark speculates a task at 1.5× the stage median once a quantile of
# tasks completed; extraction durations here are far noisier than Spark's
# cluster tasks (sidecar mmap hits vs HTTP round-trips), so the default
# multiplier is more conservative and the floor avoids speculating
# millisecond shards on scheduler jitter.
SPECULATION_MULTIPLIER = 4.0
SPECULATION_MIN_COMPLETED = 6
SPECULATION_FLOOR_SECONDS = 0.05


def completion_parallel_map(
    fn: Callable[[T], R],
    items: Sequence[T] | Iterable[T],
    workers: int,
    lookahead: int = 2,
) -> Iterator[R]:
    """Yield ``fn(item)`` in COMPLETION order — whichever extraction
    finishes first flows downstream first — with the same bounded
    window as :func:`ordered_parallel_map` (≤ ``workers + lookahead``
    in flight). ``workers <= 1`` degrades to the serial loop.

    The head-of-line blocking the ordered map accepts to keep results
    bit-identical is pure wasted wall-clock for consumers whose
    accumulation is ORDER-INDEPENDENT: the packed Gramian accumulates
    exact integer co-occurrence counts, so ``G`` is bit-identical under
    any shard arrival order (pinned by test) — a slow remote shard then
    never stalls the device behind it. Use the ordered map whenever the
    consumer's output depends on element order (block packing for
    checkpoint digests, printed records).

    NO INTER-PHASE BARRIER: ``items`` is pulled by a dedicated feeder
    thread, so a completed result reaches the consumer the moment it
    finishes even while the items iterator itself is BLOCKED producing
    the next element. The pre-cold-stream implementation pulled items
    and drained results on one thread, which parked finished work
    behind a slow upstream (a wire fetch between windows) — exactly
    the phase barrier the streaming cold path exists to remove; the
    acceptance test pins the overlap on the trace timeline.

    A worker exception surfaces at the point it is DRAINED (not at the
    failed item's submission position); an items-iterator exception
    surfaces after the results already in flight; remaining in-flight
    work is abandoned to the executor's shutdown, like the ordered map.
    """
    if workers <= 1:
        for item in items:
            yield fn(item)
        return

    import queue as _queue
    import threading
    from concurrent.futures import Future, ThreadPoolExecutor

    window = workers + max(0, lookahead)
    done_q: _queue.Queue = _queue.Queue()
    slots = threading.Semaphore(window)  # bounds results in flight
    stop = threading.Event()
    _END = object()
    state = {"submitted": 0}
    pending: set = set()
    plock = threading.Lock()

    with ThreadPoolExecutor(max_workers=workers) as pool:

        def feed() -> None:
            try:
                for item in items:
                    slots.acquire()
                    if stop.is_set():
                        return
                    fut = pool.submit(fn, item)
                    state["submitted"] += 1
                    with plock:
                        pending.add(fut)

                    def _done(f) -> None:
                        with plock:
                            pending.discard(f)
                        done_q.put(f)

                    fut.add_done_callback(_done)
            except BaseException as e:  # noqa: BLE001 — re-raised below
                done_q.put(e)
            finally:
                done_q.put(_END)

        feeder = threading.Thread(
            target=feed, name="completion-map-feed", daemon=True
        )
        feeder.start()
        end_seen = False
        yielded = 0
        try:
            while not (end_seen and yielded == state["submitted"]):
                got = done_q.get()
                if got is _END:
                    end_seen = True
                    continue
                if isinstance(got, Future):
                    slots.release()
                    yielded += 1
                    yield got.result()
                else:
                    raise got  # the items iterator itself failed
        finally:
            stop.set()
            slots.release()  # unblock a feeder parked on a full window
            with plock:
                leftover = list(pending)
            # Cancel OUTSIDE plock: cancelling a not-yet-started future
            # runs its done callbacks inline on this thread, and _done
            # re-acquires plock — holding it here self-deadlocks.
            for fut in leftover:
                fut.cancel()


class _Attempt:
    """One submitted extraction: its future plus the in-thread start time
    (None until a pool thread actually begins — queue time must not count
    toward straggler detection)."""

    __slots__ = ("future", "started")

    def __init__(self):
        self.future = None
        self.started = None


def ordered_parallel_map(
    fn: Callable[[T], R],
    items: Sequence[T] | Iterable[T],
    workers: int,
    lookahead: int = 2,
    speculate: bool = False,
    on_speculate: Optional[Callable[[T], None]] = None,
) -> Iterator[R]:
    """Yield ``fn(item)`` in input order, computing up to ``workers``
    items concurrently with at most ``workers + lookahead`` in flight
    (bounding memory to a few shards' worth regardless of manifest
    length). ``workers <= 1`` degrades to the plain serial loop — no
    threads, no queues, identical failure timing.

    A worker exception surfaces at the position of ITS item (in-order,
    like the serial loop would), after which iteration stops; remaining
    in-flight work is abandoned to the executor's shutdown.

    ``speculate=True`` adds Spark-style speculative execution (the
    straggler half of Spark's elasticity; task re-execution for LOST
    work is the elastic checkpoint layer's job): when the head-of-line
    item — the only one blocking output — has been RUNNING longer than
    ``SPECULATION_MULTIPLIER`` × the median completed duration (with at
    least ``SPECULATION_MIN_COMPLETED`` samples), a duplicate attempt
    launches on a spare thread and whichever attempt finishes first
    wins. Extraction is idempotent and deterministic, so both attempts
    produce identical results and the winner's identity cannot change
    the output. A failed attempt defers to the survivor — speculation
    doubles as a retry when the original dies slowly — and the failure
    only surfaces if BOTH attempts fail. ``on_speculate(item)`` fires at
    each launch (observability: the driver counts these).
    """
    if workers <= 1:
        for item in items:
            yield fn(item)
        return

    import collections
    from concurrent.futures import FIRST_COMPLETED, ThreadPoolExecutor, wait

    durations: list = []

    def submit(pool, item) -> _Attempt:
        att = _Attempt()

        def run():
            att.started = time.monotonic()
            out = fn(item)
            durations.append(time.monotonic() - att.started)
            return out

        att.future = pool.submit(run)
        return att

    def drain_head(head_item, head: _Attempt, spare_pool) -> R:
        """Block for the head-of-line result, speculating if it lags."""
        attempts = [head]
        while True:
            # Check EVERY attempt for a winner at the top of the loop —
            # not just the futures the last wait() reported. An attempt
            # can complete in the gap between a wait() timeout (where a
            # speculation launches) and the next wait set construction;
            # checking only newly-done futures would silently drop that
            # winner and block on the loser.
            for a in attempts:
                if a.future.done() and a.future.exception() is None:
                    return a.future.result()
            # Wait ONLY on unfinished attempts: a completed-failed future
            # left in the wait set would make wait() return instantly
            # every iteration — a 100%-CPU spin for as long as the
            # survivor runs.
            live = [a for a in attempts if not a.future.done()]
            if not live:
                # Every attempt failed; surface the ORIGINAL's error
                # (in-order semantics).
                return attempts[0].future.result()
            deadline = None
            timeout = None
            if speculate and len(attempts) == 1:
                if (
                    len(durations) >= SPECULATION_MIN_COMPLETED
                    and head.started is not None
                ):
                    threshold = max(
                        SPECULATION_MULTIPLIER
                        * statistics.median(tuple(durations)),
                        SPECULATION_FLOOR_SECONDS,
                    )
                    deadline = head.started + threshold
                    timeout = max(0.0, deadline - time.monotonic())
                else:
                    # Not yet eligible; re-check as siblings complete.
                    timeout = 0.1
            wait(
                {a.future for a in live},
                timeout=timeout,
                return_when=FIRST_COMPLETED,
            )
            # The top-of-loop scan handles whatever completed (winner →
            # return; failure → dropped from the next wait set).
            if (
                deadline is not None
                and time.monotonic() >= deadline
                and len(attempts) == 1
                and not head.future.done()
            ):
                # Deadline passed with the head still running: speculate.
                if on_speculate is not None:
                    on_speculate(head_item)
                attempts.append(submit(spare_pool, head_item))

    window = workers + max(0, lookahead)
    with ThreadPoolExecutor(max_workers=workers) as pool, ThreadPoolExecutor(
        max_workers=workers, thread_name_prefix="speculate"
    ) as spare:
        # The spare pool exists so a speculative attempt starts
        # immediately instead of queueing behind the main pool's backlog
        # (Spark launches speculative copies on free executors). It is
        # sized like the main pool, not 1: an abandoned duplicate whose
        # original won keeps running until its IO completes, and a
        # single-thread spare would let one such zombie silently queue
        # every later speculation behind it. Generator exhaustion joins
        # all attempts (pool shutdown waits), so a wedged abandoned
        # duplicate delays RETURN, never correctness — sources put
        # timeouts on their IO for exactly this reason.
        pending = collections.deque()
        try:
            for item in items:
                pending.append((item, submit(pool, item)))
                if len(pending) >= window:
                    head_item, head = pending.popleft()
                    yield drain_head(head_item, head, spare)
            while pending:
                head_item, head = pending.popleft()
                yield drain_head(head_item, head, spare)
        finally:
            for _, att in pending:
                att.future.cancel()
