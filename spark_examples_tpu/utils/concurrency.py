"""Ordered bounded-lookahead parallel map — shard-parallel host ingest.

The cold all-autosomes run is HOST-bound: the device Gramian is
sub-second per chr20 while per-shard extraction (sidecar slice + remap,
JSON parse fallback, or an HTTP round-trip per shard) runs serially.
This is the composition round 2 left open (NOTES round-3 agenda #3):
N workers extract shards concurrently while the consumer — the single
device accumulator — receives results in EXACT manifest order, so the
block packing and every float accumulation order is bit-identical to
the serial path; parallelism changes wall-clock, never results.

The reference gets the same shape from Spark: one task per shard, each
holding its own gRPC stream, reduced into one Gramian
(VariantsRDD.scala:205-235). Threads (not processes) because the heavy
steps release the GIL (numpy slicing/remap, socket IO) and the extracted
call lists flow to the accumulator without serialization.
"""

from __future__ import annotations

from typing import Callable, Iterable, Iterator, Sequence, TypeVar

T = TypeVar("T")
R = TypeVar("R")

__all__ = ["ordered_parallel_map"]


def ordered_parallel_map(
    fn: Callable[[T], R],
    items: Sequence[T] | Iterable[T],
    workers: int,
    lookahead: int = 2,
) -> Iterator[R]:
    """Yield ``fn(item)`` in input order, computing up to ``workers``
    items concurrently with at most ``workers + lookahead`` in flight
    (bounding memory to a few shards' worth regardless of manifest
    length). ``workers <= 1`` degrades to the plain serial loop — no
    threads, no queues, identical failure timing.

    A worker exception surfaces at the position of ITS item (in-order,
    like the serial loop would), after which iteration stops; remaining
    in-flight work is abandoned to the executor's shutdown.
    """
    if workers <= 1:
        for item in items:
            yield fn(item)
        return

    import collections
    from concurrent.futures import ThreadPoolExecutor

    window = workers + max(0, lookahead)
    with ThreadPoolExecutor(max_workers=workers) as pool:
        pending = collections.deque()
        it = iter(items)
        try:
            for item in it:
                pending.append(pool.submit(fn, item))
                if len(pending) >= window:
                    yield pending.popleft().result()
            while pending:
                yield pending.popleft().result()
        finally:
            for f in pending:
                f.cancel()
