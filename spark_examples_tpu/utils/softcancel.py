"""Cooperative run deadlines: soft-cancel at block boundaries.

A ``timeout``-style SIGKILL landing mid-TPU-dispatch has twice wedged
the relay (NOTES.md round-5 incident; the round-3/4 notes warned about
exactly this) — the kill lands between dispatch and readback and the
backend never recovers. The fix is cooperative: the run wrapper
(``scripts/tpu_run.sh``) exports an ABSOLUTE deadline and the driver
checks it at block boundaries — the one place a cancellation can land
with no dispatch in flight — exiting cleanly (code
:data:`SOFT_CANCEL_EXIT`, telemetry flushed by the CLI session) long
before the wrapper's escalation grace expires.

The deadline is an absolute unix timestamp (not a duration) so child
processes the driver spawns inherit the SAME wall-clock budget through
the environment, and a driver that starts late gets proportionally
less, never more.
"""

from __future__ import annotations

import os
import sys
import time
from typing import Optional

__all__ = [
    "SOFT_DEADLINE_ENV",
    "SOFT_CANCEL_EXIT",
    "SoftCancel",
    "deadline",
    "remaining",
    "check",
]

SOFT_DEADLINE_ENV = "SPARK_EXAMPLES_TPU_SOFT_DEADLINE"

# 75 = EX_TEMPFAIL: the run was healthy, the budget ran out — rerun
# with a checkpoint dir to resume. Distinct from the watchdog's 77
# (collective fail-stop) so operators can tell budget from breakage.
SOFT_CANCEL_EXIT = 75


class SoftCancel(SystemExit):
    """Deadline reached: a CLEAN SystemExit (no traceback spam, the
    telemetry session's exit path still flushes artifacts) carrying
    :data:`SOFT_CANCEL_EXIT`."""

    def __init__(self, where: str, late_s: float):
        super().__init__(SOFT_CANCEL_EXIT)
        self.where = where
        self.late_s = late_s


def deadline(environ=os.environ) -> Optional[float]:
    """The absolute unix-epoch deadline, or None (no wrapper active).
    An unparseable value is a loud error — a mistyped deadline that
    silently disables cancellation recreates the SIGKILL hazard."""
    raw = environ.get(SOFT_DEADLINE_ENV, "").strip()
    if not raw:
        return None
    try:
        return float(raw)
    except ValueError:
        raise ValueError(
            f"{SOFT_DEADLINE_ENV}={raw!r} is not a unix timestamp "
            "(scripts/tpu_run.sh sets it; unset it to disable)"
        )


def remaining(environ=os.environ) -> Optional[float]:
    """Seconds until the deadline (negative = past), or None."""
    d = deadline(environ)
    return None if d is None else d - time.time()


def check(where: str, environ=os.environ) -> None:
    """Raise :class:`SoftCancel` when the deadline has passed.

    Called at block boundaries (between one device dispatch completing
    and the next being issued) so cancellation NEVER lands mid-dispatch.
    A no-op without the env var — zero cost on the hot path beyond one
    dict lookup.
    """
    left = remaining(environ)
    if left is None or left > 0:
        return
    from spark_examples_tpu import obs

    obs.instant("soft_cancel", scope="p", where=where, late_s=-left)
    print(
        f"Soft-cancel: run deadline reached ({-left:.1f}s past) at "
        f"{where}; exiting cleanly with code {SOFT_CANCEL_EXIT} "
        "(resume with the same --checkpoint-dir).",
        file=sys.stderr,
        flush=True,
    )
    raise SoftCancel(where, -left)
