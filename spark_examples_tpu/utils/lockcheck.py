"""Runtime backstop for the ``*_locked`` convention.

GL007 proves the convention statically for in-tree call sites, but a
static gate cannot see dynamic dispatch, monkeypatched tests, or an
embedder driving the tier directly. With
``SPARK_EXAMPLES_TPU_LOCK_CHECK=1`` every ``*_locked`` method asserts
its precondition on entry — a cheap owner/held probe — so a discipline
violation fails loudly at the exact broken call site instead of
surfacing as a torn data structure minutes later. The serving and
resilience test suites enable it for their whole run.

Disabled (the default) this is one dict lookup per call — nothing on
any hot path anyway, since ``*_locked`` methods live on admission and
bookkeeping code, not in kernels.
"""

from __future__ import annotations

import os
from typing import Any

__all__ = ["LOCK_CHECK_ENV", "lock_check_enabled", "assert_lock_held"]

LOCK_CHECK_ENV = "SPARK_EXAMPLES_TPU_LOCK_CHECK"


def lock_check_enabled() -> bool:
    """Read per call (not cached): test fixtures toggle the env var
    around individual suites."""
    return os.environ.get(LOCK_CHECK_ENV, "") not in ("", "0")


def assert_lock_held(lock: Any, what: str = "") -> None:
    """Assert the calling thread satisfies a ``*_locked`` precondition.

    RLock and Condition expose ``_is_owned()`` (CPython implementation
    detail, but stable since 2.x) — the precise check: held BY THIS
    THREAD. A plain Lock has no owner concept; ``locked()`` (held by
    somebody) is the best cheap probe and still catches the common bug
    of calling with no lock at all.
    """
    if not lock_check_enabled():
        return
    is_owned = getattr(lock, "_is_owned", None)
    held = bool(is_owned()) if callable(is_owned) else lock.locked()
    if not held:
        raise AssertionError(
            f"*_locked convention violated: {what or 'callee'} requires "
            f"its owning lock ({lock!r}) to be held by the caller — "
            "see docs/CONCURRENCY.md"
        )
