"""Numerics/race debugging aids.

The reference's safety story is immutability plus Spark's driver-merged
accumulators (SURVEY.md §5 — no sanitizers, no race detection). The moving
parts here that can race are explicit and few: the prefetch producer
thread (`arrays/feed.py`, bounded queue + stop event), the bridge server
threads (per-connection state only), and the IoStats counters (lock-held
increments). This module adds the numerics half: a toggle for JAX's
NaN/Inf tracers and a checked-accumulation helper used by tests to prove
the Gramian stays within exact-f32 range.
"""

from __future__ import annotations

import contextlib
from typing import Iterator

import jax
import jax.numpy as jnp

__all__ = ["debug_numerics", "assert_exact_f32_range"]


@contextlib.contextmanager
def debug_numerics(enable: bool = True) -> Iterator[None]:
    """Enable jax_debug_nans/jax_debug_infs for the enclosed region."""
    if not enable:
        yield
        return
    prev_nans = jax.config.jax_debug_nans
    prev_infs = jax.config.jax_debug_infs
    jax.config.update("jax_debug_nans", True)
    jax.config.update("jax_debug_infs", True)
    try:
        yield
    finally:
        jax.config.update("jax_debug_nans", prev_nans)
        jax.config.update("jax_debug_infs", prev_infs)


def assert_exact_f32_range(g) -> None:
    """Fail if any Gramian entry exceeds 2^24 — the bound below which f32
    accumulation of 0/1 products is exact (ops/gramian.py docstring).

    Beyond it, switch to ``accum_dtype=jnp.int32`` (exact to 2^31) — see
    :func:`spark_examples_tpu.ops.gramian`.
    """
    mx = float(jnp.max(jnp.asarray(g)))
    if mx >= float(1 << 24):
        raise AssertionError(
            f"Gramian entry {mx} ≥ 2^24: f32 accumulation no longer exact; "
            "use accum_dtype=jnp.int32"
        )
