"""Replica identity + lease management for the replicated serving plane.

Each ``serve-cohort`` process gets a **replica identity** and a
:class:`LeaseManager` holding a lease on its own name in the shared
:class:`~spark_examples_tpu.store.DurableStore`:

- the lease carries a **monotonic fencing token** (bumped on every
  acquisition — first grab, re-grab after expiry, takeover), renewed by
  a heartbeat daemon thread every ``heartbeat_s`` against a TTL of
  ``ttl_s``;
- a replica whose renewal is rejected (a peer took its lease over) is a
  **zombie**: its state drops to ``lost``, and every fenced write it
  attempts afterwards — journal appends, shared job-index puts, delta
  write-throughs — is rejected loudly with
  :class:`~spark_examples_tpu.store.FencedWriteError`, never
  torn-merged into shared state;
- a peer whose lease **expired** (it stopped heartbeating: killed,
  wedged, partitioned) is adoptable: :meth:`LeaseManager.takeover`
  CAS-claims the dead peer's lease (bumping its token, which fences the
  peer should it wake), after which the serving tier replays the peer's
  journal and re-queues its in-flight jobs in submission order;
- a replica that cannot reach the store **degrades, never crashes**: it
  keeps serving in single-replica local mode, the
  ``serving_store_degraded`` gauge goes to 1, and replica-dependent
  HTTP paths answer 503 + Retry-After until the store returns.

The lease state machine (pinned in docs/RESILIENCE.md):

    init --start()--> acquired --renew ok--> acquired
    acquired --renew CAS-rejected--> lost         (terminal: zombie)
    acquired --store unreachable--> acquired+degraded
    acquired+degraded --renew ok--> acquired      (recovered)
    acquired --stop()--> released                 (terminal)

Every transition emits a ``lease_transition`` instant and counts
``serving_lease_total{outcome}``.
"""

from __future__ import annotations

import os
import socket
import threading
import uuid
from typing import Dict, List, Optional

from spark_examples_tpu.store import (
    DurableStore,
    FencedWriteError,
    Lease,
    StoreError,
)
from spark_examples_tpu.utils.lockcheck import assert_lock_held

__all__ = [
    "DEFAULT_HEARTBEAT_S",
    "DEFAULT_LEASE_TTL_S",
    "LeaseManager",
    "generate_replica_id",
]

DEFAULT_LEASE_TTL_S = 5.0
DEFAULT_HEARTBEAT_S = 1.0

# Store-key namespaces the replica plane writes under.
JOB_INDEX_PREFIX = "jobs/"
ADOPTED_PREFIX = "adopted/"


def generate_replica_id() -> str:
    """A replica id unique across processes and restarts — a restarted
    process is a NEW replica that adopts its predecessor's journal via
    the same expired-lease path as any other dead peer."""
    host = socket.gethostname().split(".")[0][:16] or "host"
    return f"r-{host}-{os.getpid()}-{uuid.uuid4().hex[:6]}"


def _note_lease(outcome: str, replica_id: str, token: int) -> None:
    from spark_examples_tpu import obs
    from spark_examples_tpu.obs.tracer import collection_active

    obs.instant(
        "lease_transition",
        scope="p",
        outcome=outcome,
        replica=replica_id,
        token=token,
    )
    if collection_active():
        obs.get_registry().counter(
            "serving_lease_total",
            "Replica lease transitions (outcome: acquired/renewed/lost/"
            "takeover/degraded/recovered/released/rejected_write)",
        ).labels(outcome=outcome).inc()


def _note_degraded(value: float) -> None:
    from spark_examples_tpu import obs
    from spark_examples_tpu.obs.tracer import collection_active

    if collection_active():
        obs.get_registry().gauge(
            "serving_store_degraded",
            "1 while the durable store is unreachable and this replica "
            "is serving in single-replica local mode",
        ).set(value)


class LeaseManager:
    """Owns one replica's lease lifecycle over a shared store."""

    def __init__(
        self,
        store: DurableStore,
        replica_id: Optional[str] = None,
        ttl_s: float = DEFAULT_LEASE_TTL_S,
        heartbeat_s: float = DEFAULT_HEARTBEAT_S,
    ) -> None:
        if ttl_s <= 0:
            raise ValueError(f"lease ttl must be > 0, got {ttl_s}")
        if not (0 < heartbeat_s < ttl_s):
            raise ValueError(
                f"heartbeat ({heartbeat_s}s) must be positive and "
                f"shorter than the lease ttl ({ttl_s}s) — a heartbeat "
                "that cannot outrun expiry makes every replica a zombie"
            )
        self.store = store
        self.replica_id = replica_id or generate_replica_id()
        self.ttl_s = float(ttl_s)
        self.heartbeat_s = float(heartbeat_s)
        self._lock = threading.Lock()
        self._lease: Optional[Lease] = None
        self._state = "init"
        self._degraded = False
        self._paused = False
        self._stop_event = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- guarded state ---------------------------------------------------------

    def _set_state_locked(
        self, state: Optional[str] = None, degraded: Optional[bool] = None
    ) -> None:
        assert_lock_held(self._lock, "LeaseManager._set_state_locked")
        if state is not None:
            self._state = state
        if degraded is not None:
            self._degraded = degraded

    def state(self) -> str:
        with self._lock:
            return self._state

    def degraded(self) -> bool:
        with self._lock:
            return self._degraded

    def token(self) -> int:
        with self._lock:
            return self._lease.token if self._lease is not None else 0

    def lease(self) -> Optional[Lease]:
        with self._lock:
            return self._lease

    # -- lifecycle -------------------------------------------------------------

    def start(self) -> bool:
        """Acquire this replica's lease and start the heartbeat thread.

        Returns False — degraded single-replica local mode — when the
        store is unreachable; raises :class:`FencedWriteError` when a
        LIVE peer already holds this replica id (a configuration error
        that must not be survived silently)."""
        try:
            lease = self.store.lease_acquire(
                self.replica_id, self.replica_id, self.ttl_s
            )
        except StoreError as e:
            self._enter_degraded(f"lease acquire: {e}")
            return False
        if lease is None:
            raise FencedWriteError(
                f"replica id {self.replica_id!r} is held by a live peer "
                "— replica ids must be unique per process"
            )
        with self._lock:
            self._lease = lease
            self._set_state_locked(state="acquired", degraded=False)
        _note_lease("acquired", self.replica_id, lease.token)
        _note_degraded(0.0)
        self._thread = threading.Thread(
            target=self._heartbeat_loop,
            name=f"lease-heartbeat-{self.replica_id}",
            daemon=True,
        )
        self._thread.start()
        return True

    def stop(self) -> None:
        """Stop heartbeating and release the lease (CAS: a zombie's
        release is a no-op — the lease already moved on)."""
        self._stop_event.set()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=2 * self.heartbeat_s + 1.0)
        with self._lock:
            lease = self._lease
            state = self._state
            self._set_state_locked(state="released")
        if lease is not None and state == "acquired":
            try:
                self.store.lease_release(lease)
                _note_lease("released", self.replica_id, lease.token)
            except StoreError:
                pass

    def pause(self) -> None:
        """Chaos hook: stop renewing WITHOUT stopping the process — the
        SIGSTOP/GC-pause shape. The lease expires, a peer takes over,
        and this replica wakes up a zombie whose writes must be
        rejected (the zombie-fencing pin)."""
        with self._lock:
            self._paused = True

    def resume(self) -> None:
        with self._lock:
            self._paused = False

    def _heartbeat_loop(self) -> None:
        while not self._stop_event.wait(self.heartbeat_s):
            with self._lock:
                lease = self._lease
                paused = self._paused
                state = self._state
            if paused or lease is None or state not in ("acquired",):
                continue
            try:
                renewed = self.store.lease_renew(lease, self.ttl_s)
            except FencedWriteError as e:
                with self._lock:
                    self._set_state_locked(state="lost")
                _note_lease("lost", self.replica_id, lease.token)
                print(
                    f"[replica {self.replica_id}] lease LOST — this "
                    f"process is a zombie; shared-state writes will be "
                    f"rejected: {e}"
                )
                return
            except StoreError as e:
                self._enter_degraded(f"lease renew: {e}")
                continue
            recovered = False
            with self._lock:
                self._lease = renewed
                recovered = self._degraded
                self._set_state_locked(degraded=False)
            _note_lease(
                "recovered" if recovered else "renewed",
                self.replica_id,
                renewed.token,
            )
            if recovered:
                _note_degraded(0.0)
                print(
                    f"[replica {self.replica_id}] store reachable again "
                    "— leaving degraded single-replica mode"
                )

    def _enter_degraded(self, why: str) -> None:
        first = False
        with self._lock:
            first = not self._degraded
            self._set_state_locked(degraded=True)
        if first:
            _note_lease("degraded", self.replica_id, self.token())
            _note_degraded(1.0)
            print(
                f"[replica {self.replica_id}] store unreachable "
                f"({why}) — degrading to single-replica local mode"
            )

    # -- fencing ---------------------------------------------------------------

    def check_fence(self) -> None:
        """Gate for every shared-state write. Raises
        :class:`FencedWriteError` when this replica is a zombie (lease
        lost or taken over); silently allows writes while degraded —
        degraded mode writes no shared state, and the local journal is
        this process's own."""
        with self._lock:
            lease = self._lease
            state = self._state
            degraded = self._degraded
        if state == "lost":
            _note_lease("rejected_write", self.replica_id, self.token())
            raise FencedWriteError(
                f"replica {self.replica_id!r} lost its lease — write "
                "rejected (zombie fencing)"
            )
        if lease is None or degraded:
            return
        try:
            self.store.check_fence(lease)
        except FencedWriteError:
            with self._lock:
                self._set_state_locked(state="lost")
            _note_lease("rejected_write", self.replica_id, lease.token)
            raise
        except StoreError as e:
            # Unreachable store is IO weather, not a fencing verdict:
            # degrade and let the write itself surface any IO error.
            self._enter_degraded(f"fence check: {e}")

    # -- peers -----------------------------------------------------------------

    def peers(self) -> List[Lease]:
        return [
            lease
            for lease in self.store.lease_list()
            if lease.name != self.replica_id
        ]

    def expired_peers(self) -> List[Lease]:
        """Dead peers whose journals are adoptable: lease expired and
        no adoption marker yet. Store trouble answers [] — peer
        adoption is a replica-mode feature, degraded mode has none."""
        try:
            now = self.store.now()
            out: List[Lease] = []
            for lease in self.peers():
                if not lease.expired(now):
                    continue
                try:
                    self.store.get(ADOPTED_PREFIX + lease.name)
                    continue  # already adopted
                except KeyError:
                    pass
                out.append(lease)
            return out
        except (StoreError, OSError):
            return []

    def takeover(self, peer: Lease) -> Optional[Lease]:
        """CAS-claim a dead peer's lease. Success bumps the peer's
        fencing token — the peer, should it wake, is a zombie from this
        instant. None when another survivor won the race."""
        try:
            got = self.store.lease_acquire(
                peer.name, self.replica_id, self.ttl_s
            )
        except StoreError as e:
            self._enter_degraded(f"takeover: {e}")
            return None
        if got is not None:
            _note_lease("takeover", self.replica_id, got.token)
        return got

    def mark_adopted(self, peer_name: str, payload: bytes) -> None:
        """Persist the adoption marker (fenced on OUR lease) after the
        peer's jobs are re-queued — written last, so a survivor that
        dies mid-adoption leaves the peer adoptable by the next one
        (at-least-once, results bit-identical either way)."""
        lease = self.lease()
        if lease is None:
            return
        self.store.put_fenced(ADOPTED_PREFIX + peer_name, payload, lease)

    def finish_takeover(self, taken: Lease) -> None:
        """Release the adopted peer's lease once its journal is
        replayed. The doc disappears; the zombie's fence check still
        rejects (lease gone ⇒ stale by definition)."""
        try:
            self.store.lease_release(taken)
        except StoreError:
            pass

    # -- introspection ---------------------------------------------------------

    def status(self) -> Dict[str, object]:
        with self._lock:
            lease = self._lease
            doc: Dict[str, object] = {
                "replica_id": self.replica_id,
                "lease_state": self._state,
                "fencing_token": lease.token if lease is not None else 0,
                "store_degraded": self._degraded,
                "ttl_s": self.ttl_s,
                "heartbeat_s": self.heartbeat_s,
            }
        try:
            doc["peers"] = sorted(lease.name for lease in self.peers())
            doc["store_ops"] = getattr(self.store, "op_counts", dict)()
        except (StoreError, OSError):
            doc["peers"] = []
        root = getattr(self.store, "root", None)
        if root is not None:
            doc["store_root"] = root
        return doc
