"""Re-entrant, session-safe PCA execution engine.

The enabling refactor for PCA-as-a-service: ``models/pca.py``'s run
loop is now callable per job (``ingest_gramian`` + ``compute_pca`` +
``collect_result``) with NO mutable state shared between runs — each
job gets a fresh :class:`VariantsPcaDriver` (per-driver cursors,
speculation counters, and jit pins stay per-job), while everything
immutable and expensive is shared across jobs:

- **compiled kernels** — jax's jit cache is process-global and keyed by
  program shape, so job #2 over the same cohort geometry pays zero
  compile time;
- **the callset index** — one immutable :class:`CallsetIndex` per
  variantset tuple, built once and handed to every driver;
- **the source** — the resident CSR sidecar / fixture the server
  fronts; its read paths are already driven concurrently by the
  shard-parallel ingest workers.

Device execution is serialized by one engine lock: ingest feeds the
device accumulator and the eigensolve owns the chip, so two jobs
interleaving dispatches would destroy both. The lock makes concurrent
submissions safe (they queue on the device in job order); host-side
work before the lock (spec resolution, index lookup) stays concurrent.
"""

from __future__ import annotations

import collections
import threading
from typing import Any, List, Tuple

__all__ = ["AnalysisEngine"]

# Distinct variantset tuples whose CallsetIndex stays resident. Bounded
# because the tuple is CLIENT-SUPPLIED on a multi-tenant surface: an
# unbounded dict keyed by request content is attacker-growable memory.
# Real servers front one or two variantsets; 8 is generous.
_INDEX_CACHE_SIZE = 8


class AnalysisEngine:
    """Runs PCA jobs against one resident source (one per server)."""

    def __init__(self, source: Any, mesh: Any = None) -> None:
        self.source = source
        self.mesh = mesh
        # One chip owner at a time — see the module docstring.
        self._device_lock = threading.Lock()
        self._index_lock = threading.Lock()
        self._indexes: "collections.OrderedDict[Tuple[str, ...], object]" = (
            collections.OrderedDict()
        )

    def index_for(self, variant_set_ids: Tuple[str, ...]) -> Any:
        """The shared immutable CallsetIndex for a variantset tuple
        (LRU-bounded; callset listings don't change under a resident
        cohort — a swapped cohort is a server restart). Order matters
        and is part of the key on purpose: the dense sample numbering
        follows variantset order."""
        from spark_examples_tpu.genomics.callsets import CallsetIndex

        with self._index_lock:
            index = self._indexes.get(variant_set_ids)
            if index is None:
                index = self._indexes[variant_set_ids] = (
                    CallsetIndex.from_source(
                        self.source, list(variant_set_ids)
                    )
                )
            self._indexes.move_to_end(variant_set_ids)
            while len(self._indexes) > _INDEX_CACHE_SIZE:
                self._indexes.popitem(last=False)
            return index

    def run(self, conf: Any) -> List[Tuple[str, float, float, str]]:
        """Execute one job: fresh driver, shared index, serialized
        device phases → ``(name, pc1, pc2, dataset)`` rows."""
        from spark_examples_tpu.models.pca import VariantsPcaDriver

        driver = VariantsPcaDriver(
            conf,
            self.source,
            mesh=self.mesh,
            index=self.index_for(tuple(conf.variant_set_ids)),
        )
        with self._device_lock:
            g = driver.ingest_gramian()
            result = driver.compute_pca(g)
        return driver.collect_result(result)
