"""Re-entrant, session-safe PCA execution engine.

The enabling refactor for PCA-as-a-service: ``models/pca.py``'s run
loop is now callable per job (``ingest_gramian`` + ``compute_pca`` +
``collect_result``) with NO mutable state shared between runs — each
job gets a fresh :class:`VariantsPcaDriver` (per-driver cursors,
speculation counters, and jit pins stay per-job), while everything
immutable and expensive is shared across jobs:

- **compiled kernels** — jax's jit cache is process-global and keyed by
  program shape, so job #2 over the same cohort geometry pays zero
  compile time;
- **the callset index** — one immutable :class:`CallsetIndex` per
  variantset tuple, built once and handed to every driver;
- **the source** — the resident CSR sidecar / fixture the server
  fronts; its read paths are already driven concurrently by the
  shard-parallel ingest workers.

Device execution is serialized by one engine lock: ingest feeds the
device accumulator and the eigensolve owns the chip, so two jobs
interleaving dispatches would destroy both. The lock makes concurrent
submissions safe (they queue on the device in job order); host-side
work before the lock (spec resolution, index lookup) stays concurrent.
"""

from __future__ import annotations

import collections
import dataclasses
import sys
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from spark_examples_tpu.serving.deltas import (
    DeltaIndex,
    gramian_base_key,
    note_delta,
)

__all__ = ["AnalysisEngine", "jit_retraces"]

# -- jit retrace accounting ---------------------------------------------------
#
# A serving tier whose specs vary geometry can silently retrace/recompile
# per job — the regression /statusz must surface. jax.monitoring emits
# one "/jax/core/compile/jaxpr_trace_duration" duration event per trace;
# counting them is the process-wide retrace count. Registered lazily
# (first engine construction) and only when jax is importable; the
# listener API is additive, so this never perturbs execution.

_RETRACE_EVENT = "/jax/core/compile/jaxpr_trace_duration"
_retrace_lock = threading.Lock()
_retrace_count = 0
_retrace_listener_installed = False


def jit_retraces() -> int:
    """Process-wide count of jaxpr traces observed so far (0 until the
    listener is installed by the first engine)."""
    with _retrace_lock:
        return _retrace_count


def _on_jax_duration_event(
    event: str, duration_secs: float, **_kw: Any
) -> None:
    global _retrace_count
    if event == _RETRACE_EVENT:
        with _retrace_lock:
            _retrace_count += 1


def _install_retrace_listener() -> None:
    global _retrace_listener_installed
    with _retrace_lock:
        if _retrace_listener_installed:
            return
        _retrace_listener_installed = True
    try:
        from jax import monitoring
    except Exception:  # pragma: no cover - jax absent
        return
    try:
        monitoring.register_event_duration_secs_listener(
            _on_jax_duration_event
        )
    except Exception:  # pragma: no cover - listener API unavailable
        pass

# Distinct variantset tuples whose CallsetIndex stays resident. Bounded
# because the tuple is CLIENT-SUPPLIED on a multi-tenant surface: an
# unbounded dict keyed by request content is attacker-growable memory.
# Real servers front one or two variantsets; 8 is generous.
_INDEX_CACHE_SIZE = 8


class AnalysisEngine:
    """Runs PCA jobs against one resident source (one per server).

    ``delta_max_samples > 0`` arms the INCREMENTAL tier
    (``serving/deltas.py``): finished Gramians are cached per base key
    (resolved variant params) + sample frame, and a job whose cohort
    differs from a cached ancestor's by at most that many samples is
    answered by exact rank-k corrections — bit-identical to
    from-scratch, with a checksum guard falling back to cold on any
    cache doubt. Meshless engines only (the tier the ``/analyze``
    surface runs); default 0 keeps direct constructions byte-identical
    to the historical engine.
    """

    def __init__(
        self,
        source: Any,
        mesh: Any = None,
        delta_max_samples: int = 0,
        delta_persist_dir: Optional[str] = None,
        delta_fence: Optional[Callable[[], None]] = None,
    ) -> None:
        self.source = source
        self.mesh = mesh
        _install_retrace_listener()
        # One chip owner at a time — see the module docstring.
        self._device_lock = threading.Lock()
        self._index_lock = threading.Lock()
        self._indexes: "collections.OrderedDict[Tuple[str, ...], object]" = (
            collections.OrderedDict()
        )
        # delta_persist_dir (normally <journal dir>/deltas; in
        # replicated serving <store root>/deltas, shared by every
        # replica) arms the write-through tier: finished Gramians
        # survive a kill -9 and re-load checksum-verified on restart
        # (serving/deltas.py). delta_fence gates those shared writes in
        # replicated mode — a zombie's Gramian is rejected loudly.
        self._deltas: Optional[DeltaIndex] = (
            DeltaIndex(
                delta_max_samples,
                persist_dir=delta_persist_dir,
                fence=delta_fence,
            )
            if delta_max_samples > 0 and mesh is None
            else None
        )

    def index_for(self, variant_set_ids: Tuple[str, ...]) -> Any:
        """The shared immutable CallsetIndex for a variantset tuple
        (LRU-bounded; callset listings don't change under a resident
        cohort — a swapped cohort is a server restart). Order matters
        and is part of the key on purpose: the dense sample numbering
        follows variantset order."""
        from spark_examples_tpu.genomics.callsets import CallsetIndex

        with self._index_lock:
            index = self._indexes.get(variant_set_ids)
            if index is None:
                index = self._indexes[variant_set_ids] = (
                    CallsetIndex.from_source(
                        self.source, list(variant_set_ids)
                    )
                )
            self._indexes.move_to_end(variant_set_ids)
            while len(self._indexes) > _INDEX_CACHE_SIZE:
                self._indexes.popitem(last=False)
            return index

    def _driver(self, conf: Any) -> Any:
        from spark_examples_tpu.models.pca import VariantsPcaDriver

        return VariantsPcaDriver(
            conf,
            self.source,
            mesh=self.mesh,
            index=self.index_for(tuple(conf.variant_set_ids)),
        )

    # -- introspection (the /healthz and /statusz sources) --------------------

    def device_lock_available(self, timeout_s: float = 0.5) -> bool:
        """Probe the device lock with a BOUNDED wait (the exit-77
        discipline: a health probe must never hang on the very wedge it
        exists to detect). False means "held for longer than the
        probe's patience" — the caller disambiguates busy-with-work
        from wedged via the tier's running-job count."""
        if not self._device_lock.acquire(timeout=max(0.0, timeout_s)):
            return False
        try:
            return True
        finally:
            self._device_lock.release()

    def delta_stats(self) -> Optional[Dict[str, int]]:
        """Delta-cache occupancy (None when the tier is unarmed)."""
        return self._deltas.stats() if self._deltas is not None else None

    # -- gang/delta compatibility probes (host-side, no device work) ----------

    def gang_key(self, conf: Any) -> str:
        """The base key gang members must share — same resolved variant
        params means same full-frame window stream."""
        return gramian_base_key(conf)

    def cohort_size(self, conf: Any, index: Any = None) -> int:
        """Restricted-cohort sample count for a job config (the N the
        gang-max bound compares against). O(|samples| + |exclude|) set
        arithmetic — NEVER builds the frame: the gang selector calls
        this under the admission-queue lock per queued job, where an
        O(N) remap build would stall every concurrent submit/pop. For
        the same reason callers already holding the job's CallsetIndex
        pass it via ``index`` — an LRU miss in :meth:`index_for` runs
        source I/O, which must never happen under the queue lock
        (the gang selector resolves the lead's index up front; members
        share it because equal base keys mean equal variantset
        tuples). Raises ValueError for the restrictions the driver
        itself would reject (unknown ids, empty cohort), so the
        selector excludes doomed jobs and they fail solo with the loud
        error."""
        if index is None:
            index = self.index_for(tuple(conf.variant_set_ids))
        samples = getattr(conf, "samples", None)
        exclude = getattr(conf, "exclude_samples", None) or ()
        if samples is None and not exclude:
            return int(index.size)
        known = index.indexes
        unknown = [s for s in (samples or ()) if s not in known] + [
            s for s in exclude if s not in known
        ]
        if unknown:
            raise ValueError(
                f"unknown sample callset id(s) in cohort restriction: "
                f"{unknown[:8]}"
            )
        if samples is None:
            size = int(index.size) - len(set(exclude))
        else:
            size = len(set(samples) - set(exclude))
        if size <= 0:
            raise ValueError(
                "cohort restriction leaves no samples"
            )
        return size

    def delta_resolvable(self, conf: Any) -> bool:
        """True when the delta index holds an ancestor for this job —
        the tier runs such jobs solo (the rank-k touch-up beats riding
        a cold gang)."""
        if self._deltas is None:
            return False
        try:
            driver = self._driver(conf)
        except ValueError:
            return False
        samples = tuple(driver.cohort.callset_of_index())
        return (
            self._deltas.resolve(gramian_base_key(conf), samples)
            is not None
        )

    # -- execution ------------------------------------------------------------

    def run(
        self, conf: Any, kind: str = "pca"
    ) -> List[Tuple[Any, ...]]:
        """Execute one job: fresh driver, shared index, serialized
        device phases → ``(name, pc1, pc2, dataset)`` rows for the
        default PCA kind, ``(name, loglik, bucket)`` rows for a
        ``pairhmm`` job (the read-side kernel pipeline against the same
        resident source). With the delta tier armed, a PCA Gramian
        resolves through the nearest cached ancestor when one is close
        enough (bit-identical either way)."""
        import jax.numpy as jnp

        if kind == "pairhmm":
            from spark_examples_tpu.models.pairhmm import PairHmmDriver

            phmm = PairHmmDriver(conf, self.source)
            with self._device_lock:
                return [tuple(row) for row in phmm.run_rows()]
        driver = self._driver(conf)
        with self._device_lock:
            if driver.sketch_selected():
                # Gramian-free: ingest returns an O(N·(k+p))
                # SketchPanel, not a G — it must never enter the
                # delta/window caches (the delta algebra corrects N×N
                # arrays, and sketch results are seed-specific), so the
                # job runs the plain tier routing end to end.
                g = driver.ingest_gramian()
            elif self._deltas is None or self.mesh is not None:
                g = driver.ingest_gramian()
            else:
                g = jnp.asarray(self._gramian_delta_aware(driver, conf))
            result = driver.compute_pca(g)
        return driver.collect_result(result)

    def _gramian_delta_aware(self, driver: Any, conf: Any) -> Any:
        """Gramian via the delta index: ancestor hit → rank-k touch-up;
        checksum mismatch or any correction error → loud fallback to
        cold; miss → cold. Every cold result (and every delta result)
        is cached for the next neighbor. Caller holds the device lock.
        """
        from spark_examples_tpu import obs

        assert self._deltas is not None
        key = gramian_base_key(conf)
        samples = tuple(driver.cohort.callset_of_index())
        entry = self._deltas.resolve(key, samples)
        if entry is not None:
            if not entry.verify():
                # The cached bytes no longer match their insert-time
                # checksum: never correct on top of a corrupt G.
                self._deltas.drop(entry)
                note_delta("fallback")
                print(
                    "WARNING: delta-cache checksum mismatch for base "
                    f"key {key[:12]}…; running cold.",
                    file=sys.stderr,
                )
                return self._gramian_cold(driver, conf, key, samples)
            added = len(set(samples) - set(entry.samples))
            removed = len(set(entry.samples) - set(samples))
            try:
                with obs.span(
                    "job.delta",
                    added=added,
                    removed=removed,
                    ancestor=entry.checksum[:12],
                ):
                    if entry.samples == samples:
                        g = entry.g
                    else:
                        windows = self._deltas.windows(key)
                        sink: Optional[list] = (
                            [] if windows is None else None
                        )
                        g = driver.ingest_gramian_delta(
                            entry.g,
                            entry.samples,
                            windows=windows,
                            window_sink=sink,
                        )
                        if sink:
                            self._deltas.put_windows(key, sink)
            except Exception as e:  # noqa: BLE001 — optimization guard
                # A correction that cannot be applied (frame drift, a
                # source that lost a callset, ...) must degrade to the
                # cold path, never fail a job the cold path would serve.
                note_delta("fallback")
                print(
                    f"WARNING: delta correction failed "
                    f"({type(e).__name__}: {e}); running cold.",
                    file=sys.stderr,
                )
                return self._gramian_cold(driver, conf, key, samples)
            note_delta("hit")
            if entry.samples != samples:
                # An exact-frame hit IS the cache entry — re-putting it
                # would copy + re-checksum an identical O(N²) array on
                # the very path whose purpose is to skip work (resolve
                # already refreshed its LRU position).
                self._deltas.put(key, samples, np.asarray(g))
            return g
        note_delta("miss")
        return self._gramian_cold(driver, conf, key, samples)

    def _gramian_cold(
        self,
        driver: Any,
        conf: Any,
        key: str,
        samples: Tuple[str, ...],
    ) -> Any:
        """From-scratch Gramian + cache warm-up: meshless
        uncheckpointed runs ride the window route so the full-frame
        windows are captured for future corrections; checkpointed runs
        keep their snapshot/resume semantics (no capture — the first
        delta against them re-streams once and captures then)."""
        assert self._deltas is not None
        if conf.checkpoint_dir:
            g = driver.ingest_gramian()
        else:
            sink: list = []
            g = driver.ingest_gramian_windows(window_sink=sink)
            self._deltas.put_windows(key, sink)
        self._deltas.put(key, samples, np.asarray(g))
        return g

    def run_gang(
        self, confs: List[Any]
    ) -> List[List[Tuple[str, float, float, str]]]:
        """Execute compatible jobs as ONE batched Gramian dispatch:
        one full-frame window stream, cohorts stacked on a leading
        batch axis through the vmapped accumulator
        (:func:`spark_examples_tpu.ops.gramian.gang_gramian_blockwise`),
        per-job finishes unstacked and run in submission order —
        results bit-identical to serial per-job execution (pinned by
        tests). All configs must share a base key (the tier's
        compatibility predicate guarantees it; violated = loud error).
        """
        import jax.numpy as jnp

        from spark_examples_tpu.ops.gramian import gang_gramian_blockwise

        if not confs:
            return []
        if len(confs) == 1:
            return [self.run(confs[0])]
        keys = {gramian_base_key(c) for c in confs}
        if len(keys) != 1:
            raise ValueError(
                f"gang members disagree on the Gramian base key: "
                f"{sorted(keys)}"
            )
        key = keys.pop()
        # Gang members never checkpoint (small cohorts; replay re-runs
        # them bit-identically), and the batched path is meshless.
        confs = [
            dataclasses.replace(c, checkpoint_dir=None) for c in confs
        ]
        drivers = [self._driver(c) for c in confs]
        sizes = [int(d.cohort.size) for d in drivers]
        n_max = max(sizes)
        remaps = []
        for d in drivers:
            if d._sample_remap is not None:
                remaps.append(np.asarray(d._sample_remap, dtype=np.int64))
            else:
                remaps.append(
                    np.arange(d.index.size, dtype=np.int64)
                )
        out: List[List[Tuple[str, float, float, str]]] = []
        with self._device_lock:
            windows = (
                self._deltas.windows(key)
                if self._deltas is not None
                else None
            )
            # Capture only when a delta index exists to consume it:
            # with deltas off, buffering every full-frame window for
            # the whole dispatch would hold GBs at biobank V for no
            # reader.
            sink: Optional[list] = (
                []
                if windows is None and self._deltas is not None
                else None
            )

            def stream() -> Any:
                for window in drivers[0]._cohort_windows(restrict=False):
                    if sink is not None:
                        sink.append(window)
                    yield window

            g = gang_gramian_blockwise(
                windows if windows is not None else stream(),
                remaps,
                n_max,
                block_variants=confs[0].block_variants,
            )
            if self._deltas is not None and sink is not None:
                self._deltas.put_windows(key, sink)
            for b, driver in enumerate(drivers):
                n_b = sizes[b]
                g_b = np.ascontiguousarray(g[b, :n_b, :n_b])
                if self._deltas is not None:
                    self._deltas.put(
                        key,
                        tuple(driver.cohort.callset_of_index()),
                        g_b,
                    )
                result = driver.compute_pca(jnp.asarray(g_b))
                out.append(driver.collect_result(result))
        return out
