"""The journal/job-record key registry — ONE source for both sides.

The serving tier's crash safety rests on a compatibility contract that
was, until this round, enforced only by convention and by the
mixed-version replay tests: every key a journal writer emits must be a
key the replay readers know, and every key added after round 6 must be
ABSENCE-TOLERANT on read (``e.get(...)``, or a subscript guarded by a
``.get`` of the same key), because journals accumulate across server
generations and an old record carries none of the new keys. Drift in
either direction is how a "compatible" change silently orphans every
pre-upgrade journal.

This module is the GL003 schema-sharing pattern applied to durability:
the writer sites (``tier._submit_event`` and friends), the replay
readers (``tier._replay`` / ``_replay_foreign``), the
``journal-compat`` graftlint rule (GL015), the registry-generated
mixed-version replay test, and the crashsim journal scenario all draw
from THESE name sets — one source, shared, so the static gate, the
runtime gate, and the code provably cannot drift apart.

Stdlib-only and import-light on purpose: graftlint loads this file
directly (``importlib`` from source path, the ``validate_trace.py``
discipline), so it must never grow a jax/numpy import.
"""

from __future__ import annotations

# Journal event kinds (the "e" key's closed value set). One line per
# event per state transition, append-only; replay folds them in order.
JOURNAL_EVENT_KINDS = ("submit", "start", "done", "fail")

# Keys a reader may assume present and subscript directly. "e" and
# "id" have ridden every event since round 6; "spec" rides every
# submit since round 6 (readers subscript it inside a tolerant
# try/except that drops the record loudly — a submit without a spec
# is corruption, not version skew).
JOURNAL_REQUIRED_KEYS = frozenset({"e", "id", "spec"})

# Keys that joined after the first journal shipped (or are simply
# optional per event kind). Readers MUST access these tolerantly —
# ``e.get(k)`` or a subscript guarded by ``e.get(k)`` in the same
# statement — because pre-upgrade journals do not carry them:
#   seq/key/ts/rows/error  round 6 (per-kind optional)
#   trace                  round 16 (admission-minted trace id)
#   replica/fence          round 17 (replicated serving)
JOURNAL_OPTIONAL_KEYS = frozenset(
    {"seq", "key", "ts", "trace", "rows", "error", "replica", "fence"}
)

JOURNAL_KEYS = JOURNAL_REQUIRED_KEYS | JOURNAL_OPTIONAL_KEYS

# The serialized Job record (HTTP /jobs surface + the shared-store
# ``jobs/<id>`` index). "replica"/"fence" are stamped only by
# ``tier._index_put`` in replicated mode; "trace_id"/"error"/"result"
# are conditional — every consumer treats the whole record as a
# tolerant dict (``peer_job_record`` returns it verbatim).
JOB_RECORD_REQUIRED_KEYS = frozenset(
    {"id", "state", "tenant", "cached", "submitted_unix", "spec"}
)
JOB_RECORD_OPTIONAL_KEYS = frozenset(
    {"trace_id", "error", "result", "replica", "fence"}
)
JOB_RECORD_KEYS = JOB_RECORD_REQUIRED_KEYS | JOB_RECORD_OPTIONAL_KEYS
