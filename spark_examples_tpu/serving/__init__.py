"""PCA-as-a-service: the multi-tenant analysis job tier.

``genomics/service.py`` fronts this package with ``POST /analyze`` +
``GET /jobs/<id>``: clients submit cohort specs and the server
schedules PCA runs against its resident source. Robustness is the
architecture — admission control (circuit breaker + bounded priority
queue + per-tenant quotas + 429/Retry-After shedding), a crash-safe
append-only job journal with deterministic replay, a result cache with
single-flight dedup keyed on the cohort hash, and a re-entrant
execution engine extracted from the batch driver. See
docs/OPERATIONS.md ("running the analysis service") and
docs/RESILIENCE.md (the ``serving.*`` fault seams).

Import note: this package stays jax-free at import time (the engine
imports the driver lazily), so a host-only ``serve-cohort`` without
``--analyze`` never pays the jax import.
"""

from spark_examples_tpu.serving.deltas import (
    DeltaIndex,
    gramian_base_key,
)
from spark_examples_tpu.serving.engine import AnalysisEngine
from spark_examples_tpu.serving.jobs import (
    Job,
    JobJournal,
    JobSpec,
    cohort_key,
    job_config,
)
from spark_examples_tpu.serving.queue import (
    AdmissionError,
    AdmissionQueue,
    JournalUnavailableError,
    QueueFullError,
    QuotaExceededError,
)
from spark_examples_tpu.serving.replica import (
    DEFAULT_HEARTBEAT_S,
    DEFAULT_LEASE_TTL_S,
    LeaseManager,
    generate_replica_id,
)
from spark_examples_tpu.serving.tier import AnalysisJobTier, SimulatedCrash

__all__ = [
    "AdmissionError",
    "AdmissionQueue",
    "AnalysisEngine",
    "AnalysisJobTier",
    "DEFAULT_HEARTBEAT_S",
    "DEFAULT_LEASE_TTL_S",
    "DeltaIndex",
    "Job",
    "JobJournal",
    "JobSpec",
    "JournalUnavailableError",
    "LeaseManager",
    "QueueFullError",
    "QuotaExceededError",
    "SimulatedCrash",
    "cohort_key",
    "generate_replica_id",
    "gramian_base_key",
    "job_config",
]
