"""Bounded priority admission queue with per-tenant quotas.

Load shedding is the first line of robustness for a multi-tenant
analysis server: a queue that grows without bound converts overload
into unbounded latency plus an eventual OOM, and a single greedy tenant
can starve everyone else. This queue is the explicit admission point —
``admit`` either accepts a job or raises a shed error carrying a
``retry_after`` hint DERIVED FROM the resilience layer's own backoff
engine (``RetryPolicy.backoff_delay`` over the consecutive-shed streak,
the GL005 rule applied to server-directed delays: backoff values come
from the policy engine, never ad-hoc constants), which the HTTP surface
ships as a ``429`` + ``Retry-After`` header — the exact signal the
client tier's ``classify_http`` already honors.

Fairness: ``tenant_quota`` bounds each tenant's jobs in flight
(queued + running); capacity bounds total queue depth. Ordering is
(priority desc, submission seq asc) — stable and deterministic, so a
journal replay re-queues survivors in exactly the order an
uninterrupted server would have run them.
"""

from __future__ import annotations

import heapq
import threading
from typing import Callable, Dict, List, Optional, Tuple

from spark_examples_tpu.resilience.policy import RetryPolicy
from spark_examples_tpu.utils.lockcheck import assert_lock_held

__all__ = [
    "AdmissionError",
    "AdmissionQueue",
    "JournalUnavailableError",
    "QueueFullError",
    "QuotaExceededError",
    "DEFAULT_QUEUE_DEPTH",
    "DEFAULT_TENANT_QUOTA",
]

DEFAULT_QUEUE_DEPTH = 64
DEFAULT_TENANT_QUOTA = 8

# The shed-hint shape: starts at 1 s, doubles with the consecutive-shed
# streak, caps at 30 s. jitter=0 — the hint must be deterministic for
# the chaos tests, and client-side jitter already decorrelates retries.
_SHED_POLICY = RetryPolicy(
    base_delay=1.0, max_delay=30.0, multiplier=2.0, jitter=0.0
)


class AdmissionError(RuntimeError):
    """A shed submission; ``retry_after`` is the server-directed delay
    (seconds) the HTTP surface ships as a Retry-After header."""

    reason = "shed"

    def __init__(self, message: str, retry_after: float) -> None:
        super().__init__(message)
        self.retry_after = retry_after


class QueueFullError(AdmissionError):
    reason = "queue_full"


class QuotaExceededError(AdmissionError):
    reason = "quota"


class JournalUnavailableError(AdmissionError):
    """The job journal cannot record a submission: the crash-safety
    contract (journaled before observable) forbids running it, so the
    submission sheds retryably instead — disk conditions clear."""

    reason = "journal"


def note_shed(reason: str) -> None:
    from spark_examples_tpu import obs
    from spark_examples_tpu.obs.tracer import collection_active

    obs.instant("job_shed", scope="p", reason=reason)
    if collection_active():
        obs.get_registry().counter(
            "serving_shed_total",
            "Analysis submissions shed at admission "
            "(reason: queue_full/quota/journal)",
        ).labels(reason=reason).inc()


class AdmissionQueue:
    """Thread-safe bounded priority queue (the job tier's admission)."""

    def __init__(
        self,
        capacity: int = DEFAULT_QUEUE_DEPTH,
        tenant_quota: int = DEFAULT_TENANT_QUOTA,
        shed_policy: RetryPolicy = _SHED_POLICY,
    ) -> None:
        self.capacity = max(1, capacity)
        self.tenant_quota = max(1, tenant_quota)
        self._policy = shed_policy
        self._cv = threading.Condition()
        self._heap: List[Tuple[int, int, object]] = []
        # Per-tenant jobs in flight: queued + running, released only at
        # a terminal state — a tenant cannot reclaim quota by merely
        # having its job dequeued.
        self._in_flight: Dict[str, int] = {}
        self._shed_streak = 0

    # -- observability --------------------------------------------------------

    def _note_depth_locked(self) -> None:
        assert_lock_held(self._cv, "AdmissionQueue._note_depth_locked")
        from spark_examples_tpu import obs
        from spark_examples_tpu.obs.tracer import collection_active

        if collection_active():
            depth = float(len(self._heap))
            obs.get_registry().gauge(
                "serving_queue_depth",
                "Jobs currently queued in the analysis admission queue",
            ).set(depth)
            # Also a trace counter track: depth-over-time next to the
            # job.run spans is how a shed burst reads on the timeline.
            obs.counter("serving_queue_depth", depth=depth)
            self._note_inflight_locked()

    def _note_inflight_locked(self) -> None:
        assert_lock_held(self._cv, "AdmissionQueue._note_inflight_locked")
        from spark_examples_tpu import obs
        from spark_examples_tpu.obs.tracer import collection_active

        if collection_active():
            inflight = float(sum(self._in_flight.values()))
            obs.get_registry().gauge(
                "serving_inflight_jobs",
                "Admitted analysis jobs not yet terminal "
                "(queued + running, all tenants)",
            ).set(inflight)

    # -- admission ------------------------------------------------------------

    def _retry_after_locked(self) -> float:
        assert_lock_held(self._cv, "AdmissionQueue._retry_after_locked")
        # The streak grows the hint: a client hammering a saturated
        # queue is told to back off exponentially, exactly as the retry
        # engine itself would pace attempts (RetryPolicy.backoff_delay).
        self._shed_streak += 1
        return self._policy.backoff_delay(self._shed_streak)

    def admit(
        self, job: object, tenant: str, priority: int, seq: int
    ) -> None:
        """Accept ``job`` or raise a shed error with a retry_after hint.

        Raises :class:`QueueFullError` at capacity and
        :class:`QuotaExceededError` when the tenant's in-flight count
        (queued + running) is at quota.
        """
        with self._cv:
            if len(self._heap) >= self.capacity:
                delay = self._retry_after_locked()
                note_shed("queue_full")
                raise QueueFullError(
                    f"analysis queue full ({self.capacity} queued); "
                    f"retry in {delay:.1f}s",
                    delay,
                )
            if self._in_flight.get(tenant, 0) >= self.tenant_quota:
                delay = self._retry_after_locked()
                note_shed("quota")
                raise QuotaExceededError(
                    f"tenant {tenant!r} is at its quota of "
                    f"{self.tenant_quota} in-flight job(s); "
                    f"retry in {delay:.1f}s",
                    delay,
                )
            self._shed_streak = 0
            self._push_locked(job, tenant, priority, seq)

    def readmit(
        self, job: object, tenant: str, priority: int, seq: int
    ) -> None:
        """Re-queue a journal-replayed job, bypassing the shed checks —
        the job was already admitted by the crashed server, and resume
        must never drop work that admission accepted."""
        with self._cv:
            self._push_locked(job, tenant, priority, seq)

    def _push_locked(
        self, job: object, tenant: str, priority: int, seq: int
    ) -> None:
        assert_lock_held(self._cv, "AdmissionQueue._push_locked")
        heapq.heappush(self._heap, (-priority, seq, job))
        self._in_flight[tenant] = self._in_flight.get(tenant, 0) + 1
        self._note_depth_locked()
        self._cv.notify()

    # -- consumption ----------------------------------------------------------

    def pop(self, timeout: Optional[float] = None) -> Optional[object]:
        """Next job by (priority desc, seq asc); None on timeout."""
        with self._cv:
            if not self._heap:
                self._cv.wait(timeout)
            if not self._heap:
                return None
            _, _, job = heapq.heappop(self._heap)
            self._note_depth_locked()
            return job

    def take_compatible(
        self, pred: Callable[[object], bool], limit: int
    ) -> List[object]:
        """Pop up to ``limit`` queued jobs satisfying ``pred`` — the
        gang-batching selector: a worker that just popped a lead job
        collects the compatible queued jobs (same resolved variant
        params, small enough cohorts) to run as ONE batched dispatch.
        Selection follows pop order (priority desc, seq asc), so a gang
        is exactly the prefix of jobs a serial worker would have run
        next. Tenant in-flight slots are NOT released — the jobs are
        still in flight, exactly as if a worker had popped each one.
        ``pred`` runs under the queue lock and must not block or
        acquire the tier lock (lock hierarchy: tier → queue).
        """
        with self._cv:
            if limit <= 0 or not self._heap:
                return []
            taken: List[object] = []
            kept: List[Tuple[int, int, object]] = []
            for entry in sorted(self._heap):
                if len(taken) < limit and pred(entry[2]):
                    taken.append(entry[2])
                else:
                    kept.append(entry)
            if taken:
                self._heap = kept
                heapq.heapify(self._heap)
                self._note_depth_locked()
            return taken

    def _release_tenant_locked(self, tenant: str) -> None:
        assert_lock_held(
            self._cv, "AdmissionQueue._release_tenant_locked"
        )
        n = self._in_flight.get(tenant, 0)
        if n <= 1:
            self._in_flight.pop(tenant, None)
        else:
            self._in_flight[tenant] = n - 1

    def discard(self, job: object, tenant: str) -> bool:
        """Remove a rolled-back admission: drop its heap entry (a
        phantom must not consume capacity or inflate the depth gauge)
        and return its tenant slot. False when a worker already popped
        it — the slot then returns through the normal terminal
        release."""
        with self._cv:
            kept = [e for e in self._heap if e[2] is not job]
            if len(kept) == len(self._heap):
                return False
            self._heap = kept
            heapq.heapify(self._heap)
            self._release_tenant_locked(tenant)
            self._note_depth_locked()
            return True

    def release(self, tenant: str) -> None:
        """Return one in-flight slot — called when a job reaches a
        terminal state (done/failed), never at dequeue."""
        with self._cv:
            self._release_tenant_locked(tenant)
            self._note_inflight_locked()

    def depth(self) -> int:
        with self._cv:
            return len(self._heap)

    def in_flight(self, tenant: str) -> int:
        with self._cv:
            return self._in_flight.get(tenant, 0)

    def in_flight_by_tenant(self) -> Dict[str, int]:
        """Snapshot of every tenant's in-flight count (``/statusz``)."""
        with self._cv:
            return dict(self._in_flight)
