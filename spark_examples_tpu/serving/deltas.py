"""Delta index over the cohort-hash cache: nearest-ancestor Gramians.

The serving tier's result cache (``serving/tier.py``) is keyed on the
murmur3 cohort hash of the fully-resolved analysis parameters — exact
matches only. This module adds the INCREMENTAL layer underneath it: a
per-server index of finished Gramians keyed by the **base key** (the
resolved parameters that determine G's VALUES — variant sets,
references, AF filter — excluding the sample set, which determines G's
FRAME, and ``num_pc``, which only shapes the finish), each entry
carrying the cohort's sample frame and an integrity checksum. A new
submission resolves to its nearest cached ancestor — same base key,
sample set differing by at most ``delta_max_samples`` — and the engine
updates that G with exact rank-k corrections (:mod:`ops.delta`) instead
of re-accumulating from scratch.

Safety posture: deltas are an OPTIMIZATION and must never be able to
change results. Every cached G carries a murmur3 checksum taken at
insert; resolution re-verifies it, and any mismatch (or any error while
applying a correction) falls back to the cold path — counted as
``serving_delta_jobs_total{outcome="fallback"}`` so operators see decay
instead of silently losing the win. The delta math itself is
integer-exact, so a served delta is bit-identical to from-scratch
(pinned by tests); the checksum guards the CACHE, not the math.

The index also caches the base key's full-frame CSR **windows** (the
ingest stream's ``(indices, lens)`` pairs) when a cold run captured
them, so corrections are built from in-memory arrays — the O(k·N)
touch-up never re-pays the host ingest. Both stores are LRU-bounded by
bytes; jax-free at import time like the rest of ``serving/``.
"""

from __future__ import annotations

import collections
import json
import os
import sys
import threading
from typing import (
    Any,
    Callable,
    Dict,
    List,
    Optional,
    Sequence,
    Set,
    Tuple,
)

import numpy as np

__all__ = [
    "DEFAULT_DELTA_MAX_SAMPLES",
    "DEFAULT_GANG_MAX_SAMPLES",
    "DeltaEntry",
    "DeltaIndex",
    "gramian_base_key",
    "gramian_checksum",
    "note_delta",
]

# Largest sample-set symmetric difference the ancestor resolution will
# bridge (|added| + |removed|): beyond it a from-scratch run is cheaper
# than the correction. 0 disables the delta tier entirely.
DEFAULT_DELTA_MAX_SAMPLES = 16

# Cohorts at or below this many samples are gang-batching candidates
# (serving/tier.py): small-N jobs are dispatch-bound, exactly where
# stacking them along a batch axis amortizes device round-trips.
DEFAULT_GANG_MAX_SAMPLES = 256

# LRU byte budgets for the cached Gramians and the per-base-key window
# sets. Internal constants, not flags: they bound SERVER memory, and the
# correct values follow from host RAM, not workload tuning.
_GRAMIAN_CACHE_BYTES = 256 << 20
_WINDOW_CACHE_BYTES = 128 << 20
# A single G bigger than this fraction of the budget is not worth
# caching (it would evict everything else for one unlikely ancestor).
_MAX_ENTRY_FRACTION = 4


def gramian_base_key(conf: Any) -> str:
    """Hex murmur3 key over the resolved parameters that determine G's
    values — variant sets, references window, AF filter. The sample
    restriction (``samples``/``exclude_samples``) is EXCLUDED on
    purpose: cohorts differing only in samples share a base key, which
    is what makes one cohort's G another cohort's ancestor. ``num_pc``
    is excluded too — it shapes the eigensolve, never G."""
    from spark_examples_tpu.genomics.hashing import murmur3_x64_128

    payload = json.dumps(
        {
            "variant_set_ids": list(conf.variant_set_ids),
            "references": conf.references,
            "all_references": bool(conf.all_references),
            "min_allele_frequency": conf.min_allele_frequency,
        },
        sort_keys=True,
        separators=(",", ":"),
    ).encode("utf-8")
    return murmur3_x64_128(payload).hex()


def gramian_checksum(g: np.ndarray) -> str:
    """Integrity digest of a cached Gramian (murmur3 over the f32
    bytes) — taken at insert, re-verified at resolve."""
    from spark_examples_tpu.genomics.hashing import murmur3_x64_128

    return murmur3_x64_128(
        np.ascontiguousarray(g, dtype=np.float32).tobytes()
    ).hex()


def note_delta(outcome: str) -> None:
    """Count one delta resolution outcome: ``hit`` (ancestor found and
    applied), ``fallback`` (checksum mismatch or correction error →
    cold), ``miss`` (no ancestor within range → cold)."""
    from spark_examples_tpu import obs

    obs.get_registry().counter(
        "serving_delta_jobs_total",
        "Delta-index resolutions for analysis jobs (hit = served by "
        "rank-k correction; fallback = guard tripped, ran cold; miss = "
        "no cached ancestor)",
    ).labels(outcome=outcome).inc()


class DeltaEntry:
    """One cached Gramian: base key + sample frame + f32 G + checksum.

    ``g`` is treated as IMMUTABLE once inserted — resolution hands the
    same array to every delta job, and the correction math never writes
    into it (``ops.delta`` gathers from it into a fresh target array).
    """

    __slots__ = ("base_key", "samples", "g", "checksum")

    def __init__(
        self, base_key: str, samples: Tuple[str, ...], g: np.ndarray
    ) -> None:
        self.base_key = base_key
        self.samples = samples
        # A PRIVATE copy, never a view: np.asarray over a jax array is
        # a zero-copy read-only view of the device buffer on CPU, and a
        # later donating dispatch could reuse that buffer — the
        # checksum guard would catch the corruption, but the cache
        # entry would be lost. Copying makes the entry self-owned.
        self.g = np.array(g, dtype=np.float32, order="C", copy=True)
        self.checksum = gramian_checksum(self.g)

    def verify(self) -> bool:
        """True when the cached bytes still match the insert-time
        checksum — the fall-back-to-cold guard."""
        return gramian_checksum(self.g) == self.checksum


class DeltaIndex:
    """Thread-safe nearest-ancestor index of cached Gramians + the
    per-base-key full-frame window cache (both byte-bounded LRU).

    ``persist_dir`` arms WRITE-THROUGH persistence: every inserted
    Gramian entry also lands as an ``.npz`` beside the job journal
    (atomic tmp→fsync→rename, the mirror-staging discipline), and a
    restarted index re-loads the directory — so a ``kill -9``'d server
    answers ±k delta queries warm instead of re-running every ancestor
    cold. The insert-time checksum rides the file and is RE-VERIFIED at
    load: a torn, truncated, or stale entry is dropped LOUDLY (warning
    + file unlink) and that cohort simply runs cold — persistence is an
    optimization and can never change results (the same posture as the
    in-memory checksum guard). The window cache is NOT persisted: the
    first delta against a re-loaded ancestor re-streams host ingest
    once and re-captures.

    In replicated serving the persist dir lives on the shared store, so
    the write-through is cross-replica: a warm delta computed on one
    replica answers on all. Two extra pieces make that safe and useful:
    ``fence`` (a zero-arg callable raising ``FencedWriteError`` when
    this process lost its lease) gates every persisted write — a
    zombie's Gramian never lands in the shared tier — and a resolve
    MISS rescans the directory for entries peers persisted since our
    last look before answering cold.
    """

    def __init__(
        self,
        max_delta_samples: int = DEFAULT_DELTA_MAX_SAMPLES,
        max_bytes: int = _GRAMIAN_CACHE_BYTES,
        max_window_bytes: int = _WINDOW_CACHE_BYTES,
        persist_dir: Optional[str] = None,
        fence: Optional[Callable[[], None]] = None,
    ) -> None:
        self.max_delta_samples = max(0, max_delta_samples)
        self.max_bytes = max(1, max_bytes)
        self.max_window_bytes = max(1, max_window_bytes)
        self._lock = threading.Lock()
        # (base_key, samples) -> entry, LRU over total G bytes.
        self._entries: "collections.OrderedDict[Tuple[str, Tuple[str, ...]], DeltaEntry]" = (
            collections.OrderedDict()
        )
        self._bytes = 0
        # base_key -> list of (indices, lens) full-frame windows.
        self._windows: "collections.OrderedDict[str, List[Tuple[np.ndarray, np.ndarray]]]" = (
            collections.OrderedDict()
        )
        self._window_bytes: Dict[str, int] = {}
        self._persist_dir = persist_dir
        self._fence = fence
        # Persisted filenames already loaded (or written) by THIS
        # process — the rescan-on-miss skips them, so a rescan costs
        # one listdir plus only the files peers added since.
        self._seen_files: Set[str] = set()
        if persist_dir is not None:
            os.makedirs(persist_dir, exist_ok=True)
            loaded = self._load_persisted(sweep_partials=True)
            if loaded:
                print(
                    f"Delta cache re-loaded: {loaded} persisted Gramian "
                    f"entr{'y' if loaded == 1 else 'ies'} "
                    f"(warm ±k answers survive the restart)."
                )

    # -- persistence ----------------------------------------------------------

    @staticmethod
    def _entry_filename(base_key: str, samples: Tuple[str, ...]) -> str:
        """Deterministic per-(base key, frame) filename — recomputable,
        so eviction/drop can unlink without tracking state."""
        from spark_examples_tpu.genomics.hashing import murmur3_x64_128

        frame = murmur3_x64_128(
            "\x00".join(samples).encode("utf-8")
        ).hex()[:16]
        return f"delta-{base_key[:16]}-{frame}.npz"

    def _entry_path(self, entry: DeltaEntry) -> Optional[str]:
        if self._persist_dir is None:
            return None
        return os.path.join(
            self._persist_dir,
            self._entry_filename(entry.base_key, entry.samples),
        )

    def _persist_entry(self, entry: DeltaEntry) -> None:
        """Write one entry through to disk (atomic: a kill mid-write
        leaves only a ``.tmp-`` partial the next load sweeps).

        The fence runs FIRST and outside the OSError handler on
        purpose: ``FencedWriteError`` is RuntimeError-shaped, so the
        disk-weather catch below can never degrade a zombie's rejected
        write into a warning."""
        from spark_examples_tpu.resilience import faults

        path = self._entry_path(entry)
        if path is None:
            return
        if self._fence is not None:
            self._fence()
        tmp = f"{path}.tmp-{os.getpid()}"
        try:
            with open(tmp, "wb") as f:
                np.savez(
                    f,
                    g=entry.g,
                    samples=np.asarray(entry.samples, dtype=np.str_),
                    base_key=np.asarray(entry.base_key),
                    checksum=np.asarray(entry.checksum),
                )
                f.flush()
                os.fsync(f.fileno())
                # Torn-write seam (InjectedFault is IOError-shaped, so
                # the disk-weather catch below handles it like any
                # mid-write crash: warn, sweep the tmp, stay in memory).
                faults.inject_write("serving.delta.write", tmp)
            os.replace(tmp, path)
            # Our own write needs no rescan pickup.
            self._seen_files.add(os.path.basename(path))
        except OSError as e:
            # Disk weather costs only restart warmth, never a result.
            print(
                f"WARNING: delta-cache persist failed for {path} "
                f"({type(e).__name__}: {e}); entry stays memory-only.",
                file=sys.stderr,
            )
            try:
                os.unlink(tmp)
            except OSError:
                pass

    def _unlink_entry(self, entry: DeltaEntry) -> None:
        path = self._entry_path(entry)
        if path is None:
            return
        try:
            os.unlink(path)
        except OSError:
            pass

    def _load_persisted(self, sweep_partials: bool = False) -> int:
        """Load persisted entries this process has not seen yet,
        loudest-possible skepticism: any unreadable/torn/checksum-
        mismatched file is warned about and unlinked — the affected
        cohort runs cold, exactly as if the entry had never been
        written. Returns the number of entries loaded.

        ``sweep_partials`` is startup-only: on a SHARED persist dir a
        ``.tmp-`` file seen mid-run may be a live peer's in-progress
        write, so rescans leave partials alone (the writer's rename
        makes them visible atomically)."""
        assert self._persist_dir is not None
        loaded = 0
        try:
            names = sorted(os.listdir(self._persist_dir))
        except OSError:
            return 0
        for name in names:
            path = os.path.join(self._persist_dir, name)
            if ".tmp-" in name:
                if sweep_partials:
                    # A kill mid-persist's partial: never parse, sweep.
                    try:
                        os.unlink(path)
                    except OSError:
                        pass
                continue
            if not name.endswith(".npz") or name in self._seen_files:
                continue
            try:
                with np.load(path, allow_pickle=False) as doc:
                    g = np.asarray(doc["g"], dtype=np.float32)
                    samples = tuple(str(s) for s in doc["samples"])
                    base_key = str(doc["base_key"])
                    checksum = str(doc["checksum"])
                if gramian_checksum(g) != checksum:
                    raise ValueError(
                        "stored checksum does not match the G bytes"
                    )
            except Exception as e:  # noqa: BLE001 — torn/stale cache file
                print(
                    f"WARNING: dropping torn/stale delta-cache entry "
                    f"{path} ({type(e).__name__}: {e}); that cohort "
                    "runs cold and re-warms.",
                    file=sys.stderr,
                )
                try:
                    os.unlink(path)
                except OSError:
                    pass
                continue
            # In-memory insert WITHOUT re-persisting (the file is the
            # source we just read); oversized entries obey the same
            # budget rule as live puts.
            entry = DeltaEntry(base_key, samples, g)
            if entry.g.nbytes > self.max_bytes // _MAX_ENTRY_FRACTION:
                # Over the per-entry budget share (a shrunken budget
                # since it was written): drop the file too, or every
                # restart re-reads and re-verifies the same dead entry.
                try:
                    os.unlink(path)
                except OSError:
                    pass
                continue
            with self._lock:
                self._entries[(base_key, samples)] = entry
                self._bytes += entry.g.nbytes
                evicted = self._evict_over_budget_locked()
            for gone in evicted:
                # A persisted set over the byte budget sheds its
                # oldest files here, or every restart would re-read,
                # re-verify, and re-evict the same dead entries.
                if gone is not entry:
                    self._unlink_entry(gone)
            self._seen_files.add(name)
            loaded += 1
        return loaded

    def _evict_over_budget_locked(self) -> List[DeltaEntry]:
        """Pop LRU entries past the byte budget; the caller unlinks the
        returned entries' files outside the lock."""
        from spark_examples_tpu.utils.lockcheck import assert_lock_held

        assert_lock_held(
            self._lock, "DeltaIndex._evict_over_budget_locked"
        )
        evicted: List[DeltaEntry] = []
        while self._bytes > self.max_bytes and len(self._entries) > 1:
            _, entry = self._entries.popitem(last=False)
            self._bytes -= entry.g.nbytes
            evicted.append(entry)
        return evicted

    # -- Gramian entries ------------------------------------------------------

    def resolve(
        self, base_key: str, samples: Sequence[str]
    ) -> Optional[DeltaEntry]:
        """Nearest cached ancestor: same base key, sample-set symmetric
        difference ≤ ``max_delta_samples`` (0 = exact frame, the
        num_pc-tweak case). Ties prefer the smallest difference, then
        the most recently used. Returns None when nothing qualifies.

        On a MISS with shared persistence armed, the directory is
        rescanned first — a peer replica may have persisted exactly
        this ancestor since our last look (one listdir; already-seen
        files are skipped) — and resolution retried once."""
        best = self._resolve_once(base_key, samples)
        if best is None and self._persist_dir is not None:
            try:
                fresh = self._load_persisted()
            except Exception:  # noqa: BLE001 — rescan is best-effort
                fresh = 0
            if fresh:
                print(
                    f"Delta cache rescanned: {fresh} entr"
                    f"{'y' if fresh == 1 else 'ies'} persisted by peer "
                    "replica(s) picked up."
                )
                best = self._resolve_once(base_key, samples)
        return best

    def _resolve_once(
        self, base_key: str, samples: Sequence[str]
    ) -> Optional[DeltaEntry]:
        want = set(samples)
        with self._lock:
            best: Optional[DeltaEntry] = None
            best_d = self.max_delta_samples + 1
            # Most-recently-used last; iterate reversed so recency
            # breaks ties at equal distance.
            for (key, _), entry in reversed(self._entries.items()):
                if key != base_key:
                    continue
                d = len(want.symmetric_difference(entry.samples))
                if d < best_d:
                    best, best_d = entry, d
                    if d == 0:
                        break
            if best is not None:
                self._entries.move_to_end((base_key, best.samples))
            return best

    def put(
        self, base_key: str, samples: Sequence[str], g: np.ndarray
    ) -> None:
        """Insert/refresh one finished Gramian (no-op when a single G
        would consume more than its budget share). With persistence
        armed the entry writes through to disk — file I/O OUTSIDE the
        index lock (the journal-append discipline: concurrent resolves
        must never stall on a slow disk)."""
        entry = DeltaEntry(base_key, tuple(samples), g)
        if entry.g.nbytes > self.max_bytes // _MAX_ENTRY_FRACTION:
            return
        key = (base_key, entry.samples)
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old.g.nbytes
            self._entries[key] = entry
            self._bytes += entry.g.nbytes
            evicted = self._evict_over_budget_locked()
        self._persist_entry(entry)
        for gone in evicted:
            if gone is not entry:
                self._unlink_entry(gone)
        # Re-check membership AFTER persisting: a concurrent put() may
        # have evicted this entry (and unlinked its file) between the
        # insert and the write above — the re-written file would then
        # orphan an entry no longer in memory. Every interleaving
        # converges: whichever of the evictor's unlink and this one
        # runs last removes the file.
        with self._lock:
            still_in = self._entries.get(key) is entry
        if not still_in:
            self._unlink_entry(entry)

    def drop(self, entry: DeltaEntry) -> None:
        """Remove a corrupt entry (checksum guard tripped) — from the
        persisted tier too, so a restart can never resurrect it."""
        key = (entry.base_key, entry.samples)
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old.g.nbytes
        self._unlink_entry(entry)

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def stats(self) -> Dict[str, int]:
        """Occupancy snapshot for ``/statusz`` (hit-ratio lives in the
        metrics registry: ``serving_delta_jobs_total{outcome=...}``)."""
        with self._lock:
            return {
                "entries": len(self._entries),
                "bytes": int(self._bytes),
                "max_bytes": int(self.max_bytes),
                "window_sets": len(self._windows),
                "window_bytes": int(sum(self._window_bytes.values())),
                "max_window_bytes": int(self.max_window_bytes),
            }

    # -- full-frame window cache ----------------------------------------------

    def windows(
        self, base_key: str
    ) -> Optional[List[Tuple[np.ndarray, np.ndarray]]]:
        """The base key's captured full-frame CSR windows (None when no
        cold run captured them yet). The returned list and its arrays
        are shared and must be treated as read-only."""
        with self._lock:
            wins = self._windows.get(base_key)
            if wins is not None:
                self._windows.move_to_end(base_key)
            return wins

    def put_windows(
        self,
        base_key: str,
        windows: Sequence[Tuple[np.ndarray, np.ndarray]],
    ) -> None:
        nbytes = int(
            sum(int(i.nbytes) + int(l.nbytes) for i, l in windows)
        )
        if nbytes > self.max_window_bytes // _MAX_ENTRY_FRACTION:
            return
        with self._lock:
            if base_key in self._windows:
                self._windows.move_to_end(base_key)
                return  # same base key => same stream; keep the first
            self._windows[base_key] = list(windows)
            self._window_bytes[base_key] = nbytes
            while (
                sum(self._window_bytes.values()) > self.max_window_bytes
                and len(self._windows) > 1
            ):
                evicted_key, _ = self._windows.popitem(last=False)
                self._window_bytes.pop(evicted_key, None)
