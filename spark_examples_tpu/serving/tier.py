"""The analysis job tier: admission → journal → workers → results.

Ties the serving pieces into the object ``genomics/service.py`` fronts
with ``POST /analyze`` + ``GET /jobs/<id>``:

- **admission**: the per-endpoint :class:`CircuitBreaker` gates
  submissions first (job-execution failures with an IO shape feed it,
  so a dead upstream source trips the fuse and new submissions shed
  instantly with a Retry-After instead of queuing jobs that will die);
  then the bounded :class:`AdmissionQueue` applies capacity and
  per-tenant quotas (429 + Retry-After, derived from
  ``RetryPolicy.backoff_delay``);
- **single-flight dedup + result cache**: submissions are keyed by
  :func:`cohort_key`; an identical in-flight submission returns the
  SAME job (one execution, any number of waiters), and a finished key
  is served from the result cache without touching the queue at all;
- **crash-safe journal**: every state transition is appended to the
  :class:`JobJournal` before it is observable, so a ``kill -9`` at any
  point leaves a journal a restarted tier replays deterministically —
  done jobs stay queryable (and warm the cache), in-flight jobs
  re-queue in original order, and a re-run produces bit-identical
  coordinates (deterministic manifest + integer-exact accumulation,
  the same invariant the chaos harness pins for ingest);
- **resumable gramians**: with a journal directory, each single-dataset
  job also gets a per-job checkpoint dir, so a job killed mid-Gramian
  resumes from its last shard-group snapshot instead of from zero.

Since the replica round the tier also has a **replicated mode**: give
it a :class:`~spark_examples_tpu.serving.replica.LeaseManager` over a
shared :class:`~spark_examples_tpu.store.DurableStore` and the journal
moves to a per-replica directory on the store, every submission and
terminal transition is mirrored into a shared job index
(``jobs/<id>``, fenced puts), per-job Gramian checkpoints live on the
store so ANY replica can resume them, and expired peers' journals are
adopted (:meth:`AnalysisJobTier.adopt_expired_peers`): their in-flight
jobs re-queue here in original submission order. A replica that lost
its lease is a zombie — every journal/index/result write it attempts is
rejected loudly with ``FencedWriteError``, never torn-merged.

Fault seams (docs/RESILIENCE.md): ``serving.job.run`` (error/stall =
job execution failure/slow job), ``serving.job.kill`` (a simulated
process death between the journaled start and execution — the
deterministic stand-in for ``kill -9`` the chaos tests drive),
``serving.journal.append`` (torn/error journal writes), and the
``store.read``/``store.write``/``store.lease`` seams under the
replicated mode.
"""

from __future__ import annotations

import collections
import json
import os
import shutil
import sys
import threading
import time
import uuid
from typing import Any, Dict, List, Optional, Tuple

from spark_examples_tpu.serving.jobs import (
    JOB_DONE,
    JOB_FAILED,
    JOB_QUEUED,
    JOB_RUNNING,
    Job,
    JobJournal,
    JobSpec,
    cohort_key,
    job_config,
    resolve_spec,
)
from spark_examples_tpu.serving.queue import (
    AdmissionQueue,
    DEFAULT_QUEUE_DEPTH,
    DEFAULT_TENANT_QUOTA,
)
from spark_examples_tpu.serving.replica import (
    JOB_INDEX_PREFIX,
    LeaseManager,
)
from spark_examples_tpu.store import FencedWriteError, Lease, StoreError
from spark_examples_tpu.utils.lockcheck import assert_lock_held

__all__ = [
    "AnalysisJobTier",
    "SimulatedCrash",
    "DEFAULT_RESULT_CACHE",
    "DEFAULT_JOB_RETENTION",
    "GANG_MAX_JOBS",
]

DEFAULT_RESULT_CACHE = 256

# Most queued compatible jobs one gang coalesces (the lead + this-1
# members): bounds the batched stack's host/device footprint at
# GANG_MAX_JOBS × gang_max_samples × block_variants int8 bytes.
GANG_MAX_JOBS = 16

# Terminal (done/failed) jobs kept queryable in memory: beyond this the
# oldest are evicted (their results live on in the LRU cache / journal).
# Without a bound, weeks of steady traffic grow the job table — and its
# retained result rows — into exactly the overload-to-OOM conversion
# the admission queue exists to prevent.
DEFAULT_JOB_RETENTION = 1024


class SimulatedCrash(RuntimeError):
    """The ``serving.job.kill`` seam fired: this worker must die AS IF
    the process were killed — no failure event reaches the journal, no
    quota is released, the job stays 'running' in the abandoned tier."""


class _ResultCache:
    """Bounded LRU of cohort_key → (job_id, rows) (thread-safe)."""

    def __init__(self, capacity: int) -> None:
        self.capacity = max(1, capacity)
        self._lock = threading.Lock()
        self._items: "collections.OrderedDict[str, Tuple[str, list]]" = (
            collections.OrderedDict()
        )

    def get(self, key: str) -> Optional[Tuple[str, list]]:
        with self._lock:
            hit = self._items.get(key)
            if hit is not None:
                self._items.move_to_end(key)
            return hit

    def put(self, key: str, job_id: str, rows: list) -> None:
        with self._lock:
            self._items[key] = (job_id, rows)
            self._items.move_to_end(key)
            while len(self._items) > self.capacity:
                self._items.popitem(last=False)

    def __len__(self) -> int:
        with self._lock:
            return len(self._items)


def _count_job(outcome: str) -> None:
    from spark_examples_tpu import obs
    from spark_examples_tpu.obs.tracer import collection_active

    if collection_active():
        obs.get_registry().counter(
            "serving_jobs_total",
            "Analysis job submissions by outcome "
            "(done/failed/cached/deduped)",
        ).labels(outcome=outcome).inc()


class AnalysisJobTier:
    """The object the HTTP surface fronts (one per server process)."""

    def __init__(
        self,
        engine: Any,
        base_config: Any,
        queue_depth: int = DEFAULT_QUEUE_DEPTH,
        tenant_quota: int = DEFAULT_TENANT_QUOTA,
        workers: int = 1,
        journal_dir: Optional[str] = None,
        cache_size: int = DEFAULT_RESULT_CACHE,
        breakers: Any = None,
        job_retention: int = DEFAULT_JOB_RETENTION,
        gang_max_samples: int = 0,
        replica: Optional[LeaseManager] = None,
    ) -> None:
        from spark_examples_tpu.resilience import BreakerSet

        self._engine = engine
        # Gang batching: cohorts at or below this many samples coalesce
        # with compatible queued jobs into one batched dispatch
        # (0 = disabled — the historical one-job-per-dispatch tier).
        self._gang_max = max(0, gang_max_samples)
        self._base = base_config
        self._queue = AdmissionQueue(queue_depth, tenant_quota)
        self._cache = _ResultCache(cache_size)
        self._breaker = (breakers or BreakerSet("serving:")).get("analyze")
        self._lock = threading.RLock()
        self._jobs: Dict[str, Job] = {}
        self._by_key: Dict[str, str] = {}  # active cohort_key → job id
        self._retention = max(1, job_retention)
        self._seq = 0
        # Replicated mode: the journal moves to THIS replica's directory
        # on the shared store, and Gramian checkpoints become shared —
        # any replica can resume them after adopting the job. A replica
        # plane that started degraded (store unreachable) falls back to
        # the local journal_dir: single-replica local mode, never a
        # crash.
        self._replica = replica
        self._store_root: Optional[str] = None
        self._peer_scan_monotonic = 0.0
        if replica is not None and not replica.degraded():
            root = getattr(replica.store, "root", None)
            if root is not None:
                self._store_root = str(root)
                journal_dir = os.path.join(
                    self._store_root, "replicas", replica.replica_id
                )
        self._journal = (
            JobJournal(journal_dir) if journal_dir else None
        )
        self._journal_dir = journal_dir
        self._stop = threading.Event()
        self._workers: List[threading.Thread] = []
        self._n_workers = max(0, workers)
        self._started_unix = time.time()
        if self._journal is not None:
            self._replay()

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> "AnalysisJobTier":
        """Spawn the worker threads (``workers=0`` = none; callers then
        drive execution with :meth:`step` — the deterministic test
        mode)."""
        for i in range(self._n_workers):
            t = threading.Thread(
                target=self._worker_loop,
                name=f"analysis-worker-{i}",
                daemon=True,
            )
            t.start()
            self._workers.append(t)
        return self

    def close(self) -> None:
        self._stop.set()
        # Unblock workers parked in pop(): the queue wakes on notify,
        # and pop() uses a bounded wait, so the stop flag is observed.
        for t in self._workers:
            t.join(timeout=10.0)
        if self._journal is not None:
            self._journal.close()
        if self._replica is not None:
            self._replica.stop()

    # -- submission -----------------------------------------------------------

    def submit(self, spec: JobSpec) -> Tuple[Job, bool]:
        """Admit one submission → ``(job, created)``.

        ``created`` False = served without new work (result cache hit
        or single-flight dedup onto an in-flight identical job). Raises
        ``CircuitOpenError`` (breaker shedding) or an
        :class:`~spark_examples_tpu.serving.queue.AdmissionError`
        (queue full / tenant quota) — the HTTP surface maps those to
        503/429 + Retry-After.
        """
        from spark_examples_tpu import obs

        key = cohort_key(spec, self._base)
        with self._lock:
            hit = self._cache.get(key)
            if hit is not None:
                job_id, rows = hit
                _count_job("cached")
                # A caller-scoped VIEW, never the original record: the
                # rows are shared across tenants by design, the
                # submitter's identity/spec are not — and mutating the
                # original's `cached` flag would corrupt its own
                # submitter's poll.
                return (
                    Job(
                        id=job_id, spec=spec, key=key, seq=-1,
                        state=JOB_DONE, cached=True, result=rows,
                    ),
                    False,
                )
            active_id = self._by_key.get(key)
            if active_id is not None:
                active = self._jobs[active_id]
                _count_job("deduped")
                return (
                    Job(
                        id=active.id, spec=spec, key=key,
                        seq=active.seq, state=active.state,
                        error=active.error, result=active.result,
                        trace_id=active.trace_id,
                    ),
                    False,
                )
            # Breaker admission: a half-open probe slot taken here is
            # settled by the job's eventual outcome (record_success /
            # record_failure in the worker), so probes measure real job
            # executions, not merely the act of queuing.
            self._breaker.before_call()  # raises CircuitOpenError
            self._seq += 1
            seq = self._seq
            # The trace id is MINTED at admission — not derived from
            # the spec or cohort key (those are shared across tenants
            # and resubmissions; the timeline is this submission's).
            job = Job(
                id=f"j-{key[:12]}-{seq}", spec=spec, key=key, seq=seq,
                trace_id=uuid.uuid4().hex[:16],
            )
            try:
                self._queue.admit(job, spec.tenant, spec.priority, seq)
            except Exception:
                # The shed verdict belongs to the queue, not the
                # endpoint: give back any half-open probe slot the
                # breaker just granted, with no verdict.
                self._breaker.release_probe()
                raise
            self._jobs[job.id] = job
            self._by_key[key] = job.id
        # Journal OUTSIDE the tier lock: the append is disk I/O, and
        # holding the lock across it would stall every /jobs poll on a
        # slow disk. The 202 still goes out only after the append
        # returns — the client-visible contract holds.
        if self._journal is not None:
            try:
                self._fence_check()
                self._journal.append(self._submit_event(job))
            except FencedWriteError:
                # A zombie must not accept work: un-admit and surface
                # the fencing rejection itself — never a retryable
                # shed, the client must fail over to a live replica.
                self._discard_admission(
                    job, key, error="fenced: replica lease lost"
                )
                raise
            except Exception as e:  # noqa: BLE001 — disk weather
                self._rollback_submit(job, key, e)  # raises
            self._index_put(job)
        obs.instant(
            "job_transition", scope="p", id=job.id, to=JOB_QUEUED
        )
        return job, True

    def _submit_event(self, job: Job) -> Dict[str, Any]:
        """The journaled submission record. The replica/fencing fields
        ride ONLY in replicated mode — a replica-less tier's records
        stay byte-identical to every earlier round's."""
        event: Dict[str, Any] = {
            "e": "submit",
            "id": job.id,
            "seq": job.seq,
            "key": job.key,
            "spec": job.spec.to_record(),
            "ts": job.submitted_unix,
            "trace": job.trace_id,
        }
        if self._replica is not None:
            event["replica"] = self._replica.replica_id
            event["fence"] = self._replica.token()
        return event

    def _fence_check(self) -> None:
        """Zombie fencing: raises ``FencedWriteError`` when this
        replica's lease was lost or taken over — its late writes must
        never merge into shared state. A replica-less tier is never
        fenced."""
        if self._replica is not None:
            self._replica.check_fence()

    def _index_put(self, job: Job) -> None:
        """Mirror one job into the shared store index (``jobs/<id>``),
        fenced on this replica's lease. Store weather degrades with a
        warning — peers recover the same facts from journal adoption —
        but a FENCING rejection is always loud."""
        replica = self._replica
        if replica is None or replica.degraded():
            return
        lease = replica.lease()
        if lease is None:
            return
        record = self.record_of(job)
        record["replica"] = replica.replica_id
        record["fence"] = lease.token
        try:
            replica.store.put_fenced(
                JOB_INDEX_PREFIX + job.id,
                json.dumps(record, sort_keys=True).encode("utf-8"),
                lease,
            )
        except StoreError as e:
            print(
                f"WARNING: shared job index write for {job.id} failed "
                f"({e}); peers will see it at journal adoption instead.",
                file=sys.stderr,
            )

    def _rollback_submit(self, job: Job, key: str, exc: Exception) -> None:
        """Crash-safety contract: a job the journal cannot record must
        not run (it would vanish from resume). Un-admit it — removing
        its heap entry so no phantom consumes capacity — and shed
        retryably; disk conditions clear. If a worker raced us and
        already took the job, let it finish (its result is correct and
        cached; its orphan journal events are skipped by replay)."""
        from spark_examples_tpu.serving.queue import (
            JournalUnavailableError,
            note_shed,
        )

        self._discard_admission(job, key, error=f"journal write failed: {exc}")
        note_shed("journal")
        raise JournalUnavailableError(
            f"analysis journal unavailable ({exc}); "
            "submission not accepted",
            5.0,
        ) from exc

    def _discard_admission(
        self, job: Job, key: str, error: Optional[str] = None
    ) -> None:
        """Un-admit a job whose durable submit record never landed
        (journal failure or fencing rejection): remove it from the
        tables and the queue so no phantom consumes capacity."""
        with self._lock:
            self._jobs.pop(job.id, None)
            if self._by_key.get(key) == job.id:
                self._by_key.pop(key, None)
            if self._queue.discard(job, job.spec.tenant):
                if job.state == JOB_QUEUED:
                    job.error = error or "admission rolled back"
                    job.state = JOB_FAILED
                # Only an un-run job gives its half-open probe slot
                # back; if a worker already took it, that execution IS
                # the probe and settles the breaker itself — releasing
                # here too would admit a second concurrent probe past
                # the bound.
                self._breaker.release_probe()

    def job(self, job_id: str) -> Optional[Job]:
        with self._lock:
            return self._jobs.get(job_id)

    def jobs(self) -> List[Job]:
        with self._lock:
            return sorted(self._jobs.values(), key=lambda j: j.seq)

    # -- snapshot serialization ------------------------------------------------
    #
    # Job objects are MUTATED by workers under the tier lock; readers
    # that serialize them must hold the same lock or they can observe a
    # torn transition (state flipped, error/result not yet written).
    # The HTTP surface reads ONLY through these three methods — the
    # fix for exactly that race, pinned by a regression test.

    def record_of(self, job: Job, include_result: bool = True) -> Dict:
        """One job serialized atomically (for a Job already in hand —
        the 202 response to a fresh submission, which a worker may
        already be finishing)."""
        with self._lock:
            return job.to_record(include_result=include_result)

    def job_record(
        self, job_id: str, include_result: bool = True
    ) -> Optional[Dict]:
        """Lookup + serialization as one atomic step (GET /jobs/<id>)."""
        with self._lock:
            job = self._jobs.get(job_id)
            if job is None:
                return None
            return job.to_record(include_result=include_result)

    def job_records(self, include_result: bool = False) -> List[Dict]:
        """Every known job serialized under one lock hold — the /jobs
        listing is a consistent snapshot, not a mid-transition blur."""
        with self._lock:
            return [
                j.to_record(include_result=include_result)
                for j in sorted(
                    self._jobs.values(), key=lambda j: j.seq
                )
            ]

    def queue_depth(self) -> int:
        return self._queue.depth()

    # -- introspection (the /statusz and /jobs?trace=1 sources) ---------------

    def status(self) -> Dict[str, Any]:
        """One introspection snapshot (``GET /statusz``): queue and
        tenant pressure, breaker state, job table shape, caches. Lock
        order is tier → queue, same as every worker path."""
        with self._lock:
            by_state: Dict[str, int] = {}
            kinds: Dict[str, int] = {}
            for j in self._jobs.values():
                by_state[j.state] = by_state.get(j.state, 0) + 1
                kinds[j.spec.kind] = kinds.get(j.spec.kind, 0) + 1
            doc: Dict[str, Any] = {
                "uptime_seconds": max(
                    0.0, time.time() - self._started_unix
                ),
                "jobs_by_state": by_state,
                "resident_job_kinds": kinds,
                "result_cache_entries": len(self._cache),
                "journal_dir": self._journal_dir,
                "workers": self._n_workers,
                "gang_max_samples": self._gang_max,
            }
        doc["queue_depth"] = self._queue.depth()
        doc["in_flight_by_tenant"] = self._queue.in_flight_by_tenant()
        doc["breakers"] = {"analyze": self._breaker.state}
        delta_stats = getattr(self._engine, "delta_stats", None)
        doc["delta_cache"] = delta_stats() if delta_stats else None
        # Outside the tier lock: LeaseManager.status() takes its own
        # lock and lists peer leases off the store — tier._lock must
        # never be held across store I/O.
        doc["replica"] = (
            self._replica.status() if self._replica is not None else None
        )
        return doc

    def replica_status(self) -> Optional[Dict[str, Any]]:
        """The replica plane's identity/lease/store snapshot (None for
        a replica-less tier) — the /statusz source. Lists peers off the
        store; use :meth:`replica_health` where boundedness matters."""
        if self._replica is None:
            return None
        return self._replica.status()

    def replica_health(self) -> Optional[Dict[str, Any]]:
        """Bounded replica bits for ``/healthz`` — in-memory lease
        state only, NO store I/O (the exit-77 discipline: a health
        probe must never hang on the very store whose weather it
        reports)."""
        if self._replica is None:
            return None
        return {
            "replica_id": self._replica.replica_id,
            "lease_state": self._replica.state(),
            "store_reachable": not self._replica.degraded(),
        }

    def peer_job_record(self, job_id: str) -> Optional[Dict]:
        """Look up a job unknown locally in the shared store index
        (cross-replica ``GET /jobs/<id>``). None = nowhere; raises
        :class:`StoreError` when the store is unreachable or this
        process is degraded — the HTTP surface maps that to 503 +
        Retry-After rather than lying with a 404."""
        replica = self._replica
        if replica is None or self._store_root is None:
            return None
        if replica.degraded():
            raise StoreError(
                "store degraded: cross-replica job lookup unavailable"
            )
        try:
            blob = replica.store.get(JOB_INDEX_PREFIX + job_id)
        except KeyError:
            return None
        try:
            record = json.loads(blob.decode("utf-8"))
        except ValueError:
            return None
        return record if isinstance(record, dict) else None

    def running_jobs(self) -> int:
        """Jobs currently in the RUNNING state (the /healthz busy-vs-
        wedged disambiguator)."""
        with self._lock:
            return sum(
                1
                for j in self._jobs.values()
                if j.state == JOB_RUNNING
            )

    def journal_writable(self) -> bool:
        """Bounded journal writability (``/healthz``). A journal-less
        tier is vacuously writable — there is nothing to wedge."""
        if self._journal is None:
            return True
        try:
            return self._journal.probe()
        except Exception:  # noqa: BLE001 — health checks never raise
            return False

    def device_available(self, timeout_s: float = 0.5) -> bool:
        """Bounded device-lock probe (``/healthz``): False when the
        engine's dispatch lock cannot be taken within ``timeout_s``.
        Pair with :meth:`running_jobs` to tell busy from wedged."""
        probe = getattr(self._engine, "device_lock_available", None)
        if probe is None:
            return True
        return bool(probe(timeout_s))

    def job_trace(self, job_id: str) -> Optional[List[Dict[str, Any]]]:
        """The job's span timeline (``GET /jobs/<id>?trace=1``): every
        event in the ambient tracer carrying its trace id. None =
        unknown job; [] = known but nothing recorded (yet, or no
        telemetry session active)."""
        from spark_examples_tpu import obs

        with self._lock:
            job = self._jobs.get(job_id)
            trace_id = job.trace_id if job is not None else None
        if job is None:
            return None
        if trace_id is None:
            return []
        return obs.get_tracer().events_for_trace(trace_id)

    # -- execution ------------------------------------------------------------

    def step(self, timeout: float = 0.0) -> bool:
        """Run one queued job — or one coalesced GANG — on the caller's
        thread (the worker body, exposed for deterministic tests and
        ``workers=0`` tiers). Returns False when nothing runnable was
        queued."""
        self._maybe_adopt_peers()
        while True:
            job = self._queue.pop(timeout=timeout)
            if job is None:
                return False
            if job.state != JOB_QUEUED:
                continue  # a rolled-back admission's stale heap entry
            self._dispatch(job)
            return True

    def _dispatch(self, job: Job) -> None:
        """One popped lead job → solo execution or a coalesced gang."""
        gang = self._gang_for(job)
        if gang:
            self._execute_gang([job] + gang)
        else:
            self._execute(job)

    def _gang_for(self, lead: Job) -> List[Job]:
        """Compatible queued jobs to batch with ``lead`` (possibly
        empty): same Gramian base key (resolved variant params — one
        shared window stream), every cohort at most ``gang_max_samples``
        samples. A lead the delta index can answer runs solo — the
        rank-k touch-up beats riding a cold gang."""
        if self._gang_max <= 0 or lead.spec.kind != "pca":
            # Gangs stack Gramian cohorts on a batch axis; a pairhmm
            # lead (or member) has no Gramian to stack and runs solo.
            return []
        engine = self._engine
        if (
            getattr(engine, "run_gang", None) is None
            or getattr(engine, "mesh", None) is not None
        ):
            return []
        try:
            lead_conf = job_config(lead.spec, self._base)
            lead_key = engine.gang_key(lead_conf)
            # Resolved HERE, outside the queue lock: an index_for LRU
            # miss runs source I/O, and the predicate below runs under
            # AdmissionQueue._cv. Members share the lead's index —
            # equal base keys mean equal variantset tuples.
            lead_index = engine.index_for(
                tuple(lead_conf.variant_set_ids)
            )
            if getattr(lead_conf, "pca_mode", "auto") == "sketch":
                # Gangs stack N×N Gramian tiles on a batch axis; a
                # sketch job has no Gramian to stack (and its result
                # is engine-specific) — it runs solo through the
                # driver's own sketch routing.
                return []
            if engine.cohort_size(lead_conf, lead_index) > self._gang_max:
                return []
            if engine.delta_resolvable(lead_conf):
                return []
        except Exception:  # noqa: BLE001 — probe failure = no gang
            return []

        def compatible(other: Any) -> bool:
            if other.state != JOB_QUEUED or other.spec.kind != "pca":
                return False  # stale entry / non-Gramian job kind
            try:
                conf = job_config(other.spec, self._base)
                return (
                    getattr(conf, "pca_mode", "auto") != "sketch"
                    and engine.gang_key(conf) == lead_key
                    and engine.cohort_size(conf, lead_index)
                    <= self._gang_max
                )
            except Exception:  # noqa: BLE001 — bad spec: solo fails it
                return False

        return self._queue.take_compatible(
            compatible, GANG_MAX_JOBS - 1
        )

    def _note_gang(self, size: int) -> None:
        from spark_examples_tpu import obs

        obs.get_registry().histogram(
            "serving_gang_size",
            "Jobs coalesced per gang-batched Gramian dispatch",
            buckets=(1.0, 2.0, 4.0, 8.0, 16.0, 32.0),
        ).observe(float(size))

    def _note_queue_age(self, job: Job) -> None:
        """Admission→start latency, per job kind — the queueing SLO
        series gang tuning and /statusz watch. Observed at the
        QUEUED→RUNNING transition; a replayed job's age spans the
        crash, which is exactly the latency its submitter saw."""
        from spark_examples_tpu import obs

        obs.get_registry().histogram(
            "serving_queue_age_seconds",
            "Admission-to-start latency of analysis jobs by kind",
        ).labels(kind=job.spec.kind).observe(
            max(0.0, time.time() - job.submitted_unix)
        )

    def _execute_gang(self, jobs: List[Job]) -> None:
        """Run a coalesced gang: per-job journal transitions exactly as
        solo execution writes them (crash-safe replay semantics are
        UNCHANGED — a kill mid-gang re-queues every started member and
        re-execution is bit-identical whatever gang it lands in), one
        batched engine dispatch, per-job finishes."""
        from spark_examples_tpu import obs
        from spark_examples_tpu.resilience import faults

        live: List[Job] = []
        with self._lock:
            for job in jobs:
                if job.state != JOB_QUEUED:
                    continue
                job.state = JOB_RUNNING
                live.append(job)
        # Disk I/O outside the tier lock (submit() reasoning).
        for job in live:
            self._journal_append_safe({"e": "start", "id": job.id})
            self._note_queue_age(job)
            with obs.trace_context(job.trace_id):
                obs.instant(
                    "job_transition", scope="p", id=job.id,
                    to=JOB_RUNNING,
                )
        for job in live:
            try:
                faults.inject("serving.job.kill", key=job.id)
            except faults.InjectedFault as e:
                # As in _execute: journal left exactly as a SIGKILL
                # would leave it — every started member re-queues on
                # replay.
                raise SimulatedCrash(str(e)) from e
        runnable: List[Job] = []
        for job in live:
            try:
                faults.inject("serving.job.run", key=job.id)
            except Exception as e:  # noqa: BLE001 — member isolation
                self._finish(job, error=f"{type(e).__name__}: {e}")
                if isinstance(e, IOError):
                    self._breaker.record_failure()
                else:
                    self._breaker.record_success()
            else:
                runnable.append(job)
        if not runnable:
            return
        self._note_gang(len(runnable))
        try:
            # One batched dispatch can only carry one thread context:
            # the gang span binds the LEAD's trace id; members are
            # recoverable from the span's job-id list.
            with obs.trace_context(runnable[0].trace_id), obs.span(
                "job.gang",
                size=len(runnable),
                jobs=",".join(j.id for j in runnable),
            ):
                rows_by_job = self._engine.run_gang(
                    [
                        job_config(j.spec, self._base)
                        for j in runnable
                    ]
                )
        except Exception as e:  # noqa: BLE001 — gang isolation boundary
            for job in runnable:
                self._finish(job, error=f"{type(e).__name__}: {e}")
                if isinstance(e, IOError):
                    self._breaker.record_failure()
                else:
                    self._breaker.record_success()
        else:
            for job, rows in zip(runnable, rows_by_job):
                self._finish(job, rows=rows)
                self._breaker.record_success()

    def _worker_loop(self) -> None:
        while not self._stop.is_set():
            self._maybe_adopt_peers()
            job = self._queue.pop(timeout=0.25)
            if job is None:
                continue
            try:
                self._dispatch(job)
            except SimulatedCrash as e:
                print(
                    f"analysis worker crashed (simulated kill): {e}",
                    file=sys.stderr,
                )
                return  # the thread dies, as the process would
            except Exception as e:  # noqa: BLE001 — worker survival
                # _execute isolates job failures itself; anything that
                # still escapes (a tier-level bug) must not silently
                # kill the only worker and wedge every queued job.
                print(
                    f"WARNING: analysis worker error on {job.id}: "
                    f"{type(e).__name__}: {e}",
                    file=sys.stderr,
                )

    def _ckpt_dir(self, job: Job) -> Optional[str]:
        # Per-job Gramian snapshots make a killed job RESUME mid-ingest
        # instead of restarting; the checkpointed route is single-
        # variantset only, so multi-set jobs simply re-run (still
        # bit-identical — the manifest is deterministic).
        if job.spec.kind != "pca":
            # Read-scoring jobs have no Gramian to snapshot; replay
            # just re-runs them (per-pair results are deterministic).
            return None
        spec_vsids = job.spec.variant_set_ids or tuple(
            self._base.variant_set_ids
        )
        if self._journal_dir is None or len(spec_vsids) != 1:
            return None
        resolved = resolve_spec(job.spec, self._base)
        if resolved["samples"] or resolved["exclude_samples"]:
            # Sample-restricted cohorts don't compose with checkpointed
            # ingest (snapshot digests are full-frame); these jobs are
            # the small delta-tier queries — replay just re-runs them.
            return None
        # Replicated mode: checkpoints are SHARED (keyed by job id,
        # which adoption preserves), so a survivor resumes a dead
        # peer's Gramian from its last shard-group snapshot instead of
        # from zero.
        base = (
            self._store_root
            if self._store_root is not None
            else self._journal_dir
        )
        return os.path.join(base, "ckpt", job.id)

    def _journal_append_safe(self, event: Dict) -> None:
        """Append a TRANSITION event (start/done/fail), degrading loudly
        on failure instead of killing the worker: losing a transition
        only costs resume WORK, never correctness — replay re-queues
        the job and re-execution is bit-identical. (Submit events are
        different: those must land or the job is rolled back.)

        The fence check runs OUTSIDE the swallowing try on purpose: a
        ``FencedWriteError`` is a correctness verdict (this replica is
        a zombie whose lease a peer took over), never disk weather —
        degrading it to a warning would be exactly the torn merge
        fencing exists to prevent."""
        if self._journal is None:
            return
        self._fence_check()
        try:
            self._journal.append(event)
        except Exception as e:  # noqa: BLE001 — disk weather
            from spark_examples_tpu import obs

            print(
                f"WARNING: journal append failed "
                f"({type(e).__name__}: {e}); job {event.get('id')} "
                "will re-run from its last durable event on resume.",
                file=sys.stderr,
            )
            obs.instant(
                "journal_append_failed",
                scope="p",
                id=str(event.get("id", "")),
                event=str(event.get("e", "")),
            )

    def _execute(self, job: Job) -> None:
        from spark_examples_tpu import obs
        from spark_examples_tpu.resilience import faults

        with self._lock:
            if job.state != JOB_QUEUED:
                # A rolled-back admission's stale heap entry (terminal
                # already): nothing to run.
                return
            job.state = JOB_RUNNING
        # Disk I/O outside the tier lock (submit() reasoning).
        self._journal_append_safe({"e": "start", "id": job.id})
        self._note_queue_age(job)
        ckpt: Optional[str] = None
        try:
            with obs.trace_context(job.trace_id):
                obs.instant(
                    "job_transition", scope="p", id=job.id,
                    to=JOB_RUNNING,
                )
                try:
                    faults.inject("serving.job.kill", key=job.id)
                except faults.InjectedFault as e:
                    # Leave the journal exactly as a SIGKILL here
                    # would: start recorded, no terminal event — and
                    # kill this worker.
                    raise SimulatedCrash(str(e)) from e
                ckpt = self._ckpt_dir(job)
                with obs.span(
                    "job.run",
                    job_id=job.id,
                    tenant=job.spec.tenant,
                    kind=job.spec.kind,
                ):
                    faults.inject("serving.job.run", key=job.id)
                    rows = self._engine.run(
                        job_config(
                            job.spec, self._base, checkpoint_dir=ckpt
                        ),
                        kind=job.spec.kind,
                    )
        except SimulatedCrash:
            raise
        except Exception as e:  # noqa: BLE001 — job isolation boundary
            self._finish(job, error=f"{type(e).__name__}: {e}")
            # IO-shaped failures (dead upstream source, injected
            # transport weather) feed the breaker; deterministic spec
            # errors are the tier ANSWERING and must not blow the fuse.
            if isinstance(e, IOError):
                self._breaker.record_failure()
            else:
                self._breaker.record_success()
        else:
            self._finish(job, rows=rows)
            self._breaker.record_success()
        # Snapshots belong to IN-FLIGHT work: any terminal outcome
        # reclaims the job's checkpoint dir (a failed id is never
        # reused — a resubmission gets a fresh seq and dir — so keeping
        # it would only leak disk). A crash skips this on purpose: the
        # re-queued same-id job resumes from these snapshots.
        if ckpt is not None:
            shutil.rmtree(ckpt, ignore_errors=True)

    def _finish(
        self,
        job: Job,
        rows: Optional[list] = None,
        error: Optional[str] = None,
    ) -> None:
        from spark_examples_tpu import obs

        # Fence BEFORE any shared-visible mutation: a zombie's result
        # must never reach the cache, the job table, or the journal —
        # the adopting peer owns this job now and will produce the
        # (bit-identical) result itself.
        self._fence_check()
        with self._lock:
            if error is None:
                # Result BEFORE state: the HTTP surface serializes
                # under this lock (record_of/job_record/job_records),
                # but in-process callers holding a Job from job()/
                # jobs() may still read its fields lock-free, checking
                # state first — they must never observe a result-less
                # 'done'.
                job.result = rows
                job.state = JOB_DONE
                self._cache.put(job.key, job.id, rows)
                event = {
                    "e": "done",
                    "id": job.id,
                    "rows": [list(r) for r in rows],
                }
                _count_job("done")
            else:
                job.error = error
                job.state = JOB_FAILED
                event = {"e": "fail", "id": job.id, "error": error}
                _count_job("failed")
            if self._by_key.get(job.key) == job.id:
                self._by_key.pop(job.key, None)
            self._queue.release(job.spec.tenant)
            self._prune_terminal_locked()
        # Disk I/O outside the tier lock (submit() reasoning).
        self._journal_append_safe(event)
        self._index_put(job)
        obs.instant(
            "job_transition", scope="p", id=job.id, to=job.state
        )

    def _prune_terminal_locked(self) -> None:
        """Evict the oldest terminal jobs beyond the retention bound
        (active jobs are never evicted; recent results stay reachable
        through the LRU cache and the journal regardless)."""
        assert_lock_held(
            self._lock, "AnalysisJobTier._prune_terminal_locked"
        )
        terminal = [
            j
            for j in self._jobs.values()
            if j.state in (JOB_DONE, JOB_FAILED)
        ]
        excess = len(terminal) - self._retention
        if excess <= 0:
            return
        terminal.sort(key=lambda j: j.seq)
        for job in terminal[:excess]:
            self._jobs.pop(job.id, None)

    # -- crash recovery -------------------------------------------------------

    def _replay(self) -> None:
        """Rebuild state from the journal: done/failed jobs restore the
        queryable table (+ warm cache); queued/running jobs re-queue in
        original submission order — the deterministic resume a killed
        server owes its clients.

        Runs under the tier lock even though it is called from
        ``__init__`` before any worker exists: the lock-discipline the
        static gate proves (GL007/GL009) is uniform, not "except during
        construction" — and a future caller replaying into a LIVE tier
        would otherwise inherit a silent race instead of a queued one.
        """
        from spark_examples_tpu import obs

        with self._lock, obs.span("job.replay", journal=self._journal.path):
            events = list(JobJournal.replay_events(self._journal_dir))
            for e in events:
                kind = e.get("e")
                if kind == "submit":
                    try:
                        spec = JobSpec.from_record(e["spec"])
                    except (KeyError, ValueError) as exc:
                        print(
                            f"WARNING: journaled spec for {e.get('id')} "
                            f"is unusable ({exc}); dropping it.",
                            file=sys.stderr,
                        )
                        continue
                    seq = int(e.get("seq", 0))
                    job = Job(
                        id=str(e["id"]),
                        spec=spec,
                        key=str(e.get("key") or cohort_key(spec, self._base)),
                        seq=seq,
                        submitted_unix=float(e.get("ts", 0.0)),
                        # Restore the admission-minted trace id so the
                        # replayed execution re-emits ITS timeline
                        # (same span names/order; durations differ).
                        trace_id=(
                            str(e["trace"]) if e.get("trace") else None
                        ),
                    )
                    self._jobs[job.id] = job
                    self._seq = max(self._seq, seq)
                elif kind in ("start", "done", "fail"):
                    job = self._jobs.get(str(e.get("id", "")))
                    if job is None:
                        continue
                    if kind == "start":
                        job.state = JOB_RUNNING
                    elif kind == "done":
                        job.state = JOB_DONE
                        job.result = [
                            tuple(r) for r in e.get("rows", [])
                        ]
                        self._cache.put(job.key, job.id, job.result)
                    else:
                        job.state = JOB_FAILED
                        job.error = str(e.get("error", ""))
            requeue = sorted(
                (
                    j
                    for j in self._jobs.values()
                    if j.state in (JOB_QUEUED, JOB_RUNNING)
                ),
                key=lambda j: j.seq,
            )
            for job in requeue:
                job.state = JOB_QUEUED
                self._by_key[job.key] = job.id
                # Bypass shed checks: the crashed server already
                # admitted these — resume must not drop admitted work.
                self._queue.readmit(
                    job, job.spec.tenant, job.spec.priority, job.seq
                )
                obs.instant(
                    "job_transition", scope="p", id=job.id, to=JOB_QUEUED
                )
            # The journal holds the server's whole history; the
            # in-memory table is bounded the same way it is live.
            self._prune_terminal_locked()
            if self._jobs:
                done = sum(
                    1 for j in self._jobs.values() if j.state == JOB_DONE
                )
                print(
                    f"Analysis journal replayed: {len(self._jobs)} "
                    f"job(s), {done} done (cache warm), "
                    f"{len(requeue)} re-queued."
                )

    # -- peer failover ----------------------------------------------------------

    def _maybe_adopt_peers(self) -> None:
        """Throttled peer-lease scan (at most one per lease TTL):
        workers call this on their dispatch path, so failover needs no
        extra thread. The tier lock guards ONLY the throttle timestamp;
        the scan itself does store I/O and must run unlocked. Never
        raises — failover trouble must not kill the worker that would
        perform the next failover."""
        replica = self._replica
        if replica is None or self._store_root is None:
            return
        now = time.monotonic()
        with self._lock:
            if now - self._peer_scan_monotonic < replica.ttl_s:
                return
            self._peer_scan_monotonic = now
        try:
            self.adopt_expired_peers()
        except Exception as e:  # noqa: BLE001 — failover must not wedge
            print(
                f"WARNING: peer adoption scan failed: "
                f"{type(e).__name__}: {e}",
                file=sys.stderr,
            )

    def adopt_expired_peers(self) -> int:
        """Scan for peers whose lease expired, take each over by CAS
        (the fencing-token bump that turns the dead peer into a fenced
        zombie if it was merely paused), and re-queue its in-flight
        jobs here in original submission order. Returns the number of
        peers adopted.

        At-least-once by construction: the ``adopted/<peer>`` marker is
        written LAST, so a survivor dying mid-adoption leaves the peer
        adoptable by the next scan — re-execution is bit-identical and
        the merge below dedups, so a double adoption is safe."""
        replica = self._replica
        if (
            replica is None
            or self._store_root is None
            or replica.degraded()
        ):
            return 0
        adopted = 0
        for peer in replica.expired_peers():
            taken = replica.takeover(peer)
            if taken is None:
                # Raced by another survivor (its CAS won), or store
                # weather — either way, not ours to adopt this round.
                continue
            self._adopt_peer(peer.name, taken)
            adopted += 1
        return adopted

    def _adopt_peer(self, peer_name: str, taken: Lease) -> None:
        from spark_examples_tpu import obs

        assert self._replica is not None and self._store_root is not None
        peer_dir = os.path.join(
            self._store_root, "replicas", peer_name
        )
        with obs.span(
            "job.adopt", peer=peer_name, fence=taken.token
        ):
            requeued = self._replay_foreign(peer_dir, peer_name)
            # Marker BEFORE release: once the marker exists the peer is
            # never re-adopted; until it exists a crash here re-runs
            # the whole adoption. Fenced on OUR lease — a survivor that
            # itself went zombie mid-adoption is rejected loudly.
            self._replica.mark_adopted(
                peer_name,
                json.dumps(
                    {
                        "by": self._replica.replica_id,
                        "fence": taken.token,
                        "requeued": requeued,
                    },
                    sort_keys=True,
                ).encode("utf-8"),
            )
            # Release the taken-over lease doc: the name disappears
            # from scans, and the zombie stays fenced regardless (a
            # MISSING lease doc fails check_fence just as a stale
            # token does).
            self._replica.finish_takeover(taken)

    def _replay_foreign(self, directory: str, peer: str) -> int:
        """Replay a dead peer's journal into THIS tier: terminal jobs
        warm the result cache and job table, in-flight jobs re-queue in
        the peer's submission order (with fresh LOCAL seqs — relative
        order is preserved, and local admissions hold their own seqs).
        Returns the number of jobs re-queued.

        Disk discipline as everywhere in this tier: the peer journal is
        read BEFORE the tier lock, adopted submit events are journaled
        AFTER it."""
        from spark_examples_tpu import obs

        try:
            events = list(JobJournal.replay_events(directory))
        except Exception as e:  # noqa: BLE001 — a torn peer journal
            print(
                f"WARNING: adopting {peer}: journal unreadable "
                f"({type(e).__name__}: {e}); its in-flight jobs are "
                "lost to this survivor (clients resubmit).",
                file=sys.stderr,
            )
            return 0
        foreign: Dict[str, Job] = {}
        order: List[str] = []
        for e in events:
            kind = e.get("e")
            if kind == "submit":
                try:
                    spec = JobSpec.from_record(e["spec"])
                except (KeyError, ValueError):
                    continue
                jid = str(e["id"])
                foreign[jid] = Job(
                    id=jid,
                    spec=spec,
                    key=str(
                        e.get("key") or cohort_key(spec, self._base)
                    ),
                    seq=int(e.get("seq", 0)),
                    submitted_unix=float(e.get("ts", 0.0)),
                    # The peer's admission-minted trace id survives
                    # adoption: the re-run emits onto the SAME timeline
                    # its submitter is polling.
                    trace_id=(
                        str(e["trace"]) if e.get("trace") else None
                    ),
                )
                order.append(jid)
            elif kind in ("start", "done", "fail"):
                job = foreign.get(str(e.get("id", "")))
                if job is None:
                    continue
                if kind == "start":
                    job.state = JOB_RUNNING
                elif kind == "done":
                    job.state = JOB_DONE
                    job.result = [tuple(r) for r in e.get("rows", [])]
                else:
                    job.state = JOB_FAILED
                    job.error = str(e.get("error", ""))
        requeue: List[Job] = []
        with self._lock:
            for jid in order:
                job = foreign[jid]
                if jid in self._jobs:
                    # Already known here — a prior partial adoption, or
                    # the peer adopted it from US earlier. Keep ours.
                    continue
                if job.state in (JOB_DONE, JOB_FAILED):
                    self._seq += 1
                    job.seq = self._seq
                    self._jobs[jid] = job
                    if job.state == JOB_DONE and job.result is not None:
                        self._cache.put(job.key, jid, job.result)
                    continue
                if self._by_key.get(job.key) is not None:
                    # An identical cohort is already active here; its
                    # result will serve the peer's submitter from the
                    # cache (same key → bit-identical rows).
                    continue
                self._seq += 1
                job.seq = self._seq
                job.state = JOB_QUEUED
                self._jobs[jid] = job
                self._by_key[job.key] = jid
                # Bypass shed checks: the dead peer already admitted
                # these — failover must not drop admitted work.
                self._queue.readmit(
                    job, job.spec.tenant, job.spec.priority, job.seq
                )
                requeue.append(job)
            self._prune_terminal_locked()
        # The adopted submissions enter THIS replica's journal so a
        # crash here resumes them yet again (transition-grade
        # durability: the shared journal on the dead peer still holds
        # them until its marker lands).
        for job in requeue:
            self._journal_append_safe(self._submit_event(job))
            obs.instant(
                "job_transition", scope="p", id=job.id, to=JOB_QUEUED
            )
        if requeue:
            print(
                f"Adopted {len(requeue)} in-flight job(s) from "
                f"expired replica {peer}."
            )
        return len(requeue)
