"""Job model + crash-safe journal for the analysis service.

A submission is a :class:`JobSpec` — the cohort spec a client POSTs to
``/analyze`` (dataset, references window, AF filter, k) plus tenant and
priority. Its :func:`cohort_key` is a MurmurHash3 x64-128 digest
(:mod:`spark_examples_tpu.genomics.hashing` — the same hash the
variant-identity join uses) over the RESOLVED analysis parameters, and
is the unit of result caching and single-flight dedup: two submissions
that would compute the same coordinates share one key, whoever their
tenants are (arxiv 1909.00954's observation that cohorts share most of
G is what makes the cache the common case, not a luxury).

The :class:`JobJournal` is the crash-safety spine: an append-only JSONL
event log (submit/start/done/fail), flushed per append and fsynced
through the watchdog's pre-exit flush hook, written under the same
torn-write discipline ``utils/checkpoint.py`` drills — the loader
tolerates a torn tail (the bytes a SIGKILL mid-append leaves) by
skipping unparseable lines with a warning, never by dying on its own
safety net. Replaying the journal reconstructs every job
deterministically: finished jobs re-populate the result cache, and
jobs that were queued or running when the process died re-queue in
original submission order.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Optional, Tuple

__all__ = [
    "Job",
    "JobJournal",
    "JobSpec",
    "JOB_KINDS",
    "cohort_key",
    "JOB_QUEUED",
    "JOB_RUNNING",
    "JOB_DONE",
    "JOB_FAILED",
]

JOB_QUEUED = "queued"
JOB_RUNNING = "running"
JOB_DONE = "done"
JOB_FAILED = "failed"

_JOURNAL_NAME = "jobs.journal.jsonl"

# Spec fields a client may set; anything else in the POST body is a
# loud 400, not a silent ignore — a typo'd "min_allele_freq" that
# silently ran unfiltered would be a correctness bug shipped as data.
_SPEC_FIELDS = frozenset(
    {
        "kind",
        "tenant",
        "variant_set_id",
        "variant_set_ids",
        "references",
        "all_references",
        "min_allele_frequency",
        "num_pc",
        "priority",
        "samples",
        "exclude_samples",
        "read_group_set_id",
        "pca_mode",
    }
)

# Analysis job kinds the tier executes. "pca" (the default, and the
# implied kind of every pre-kind journal record) runs the variant-side
# PCoA; "pairhmm" runs the read-side batched PairHMM scoring pipeline
# (models/pairhmm.py) against the served cohort's reads.
JOB_KINDS = ("pca", "pairhmm")

# Spec fields that only parameterize the variant-side analysis: a
# pairhmm submission carrying one is a loud 400, not a silent ignore
# (the same posture as unknown fields — a client that sets num_pc on a
# read-scoring job misunderstands what it asked for).
_PCA_ONLY_FIELDS = (
    "variant_set_id",
    "variant_set_ids",
    "all_references",
    "min_allele_frequency",
    "num_pc",
    "samples",
    "exclude_samples",
    "pca_mode",
)


def _sample_list(
    rec: Dict[str, Any], field: str
) -> Optional[Tuple[str, ...]]:
    """Validate + canonicalize a cohort sample-restriction field: a
    list of callset-id strings, sorted and deduplicated so permuted
    submissions are ONE cohort (one cache key, one frame — the frame
    itself orders by full-index position, driver-side)."""
    val = rec.get(field)
    if val is None:
        return None
    if not isinstance(val, (list, tuple)) or not all(
        isinstance(s, str) and s for s in val
    ):
        raise ValueError(
            f"{field} must be a list of non-empty callset-id strings"
        )
    return tuple(sorted(set(val)))


@dataclass(frozen=True)
class JobSpec:
    """One client-submitted analysis: cohort spec + tenant + priority."""

    # None (or an empty tuple) = inherit the server's configured
    # default for that field — a client submitting {} analyzes exactly
    # the cohort the server's own batch run would.
    tenant: str = "anonymous"
    variant_set_ids: Tuple[str, ...] = ()
    references: Optional[str] = None
    all_references: Optional[bool] = None
    min_allele_frequency: Optional[float] = None
    num_pc: Optional[int] = None
    priority: int = 0
    # Cohort sample restriction: `samples` keeps only the named
    # callset ids (None = all), `exclude_samples` then drops ids —
    # the spec surface the delta tier's ±k cohort queries ride.
    samples: Optional[Tuple[str, ...]] = None
    exclude_samples: Optional[Tuple[str, ...]] = None
    # Per-job PCA engine override (None = the server's configured
    # --pca-mode). The servable surface for the Gramian-free sketch
    # engine: a huge-N cohort submits {"pca_mode": "sketch"} and rides
    # the O(N·(k+p)) panel instead of 413-ing on the tile-footprint
    # bound. Validated against utils.config.PCA_MODES.
    pca_mode: Optional[str] = None
    # Job kind: "pca" (default) or "pairhmm" (read-side scoring).
    kind: str = "pca"
    # Readset filter for pairhmm jobs (None = the server's configured
    # default readset, or every readset when that too is unset).
    read_group_set_id: Optional[str] = None

    @classmethod
    def from_record(cls, rec: Dict[str, Any]) -> "JobSpec":
        """Parse + validate a client JSON body (ValueError = HTTP 400)."""
        if not isinstance(rec, dict):
            raise ValueError("analysis spec must be a JSON object")
        unknown = set(rec) - _SPEC_FIELDS
        if unknown:
            raise ValueError(
                f"unknown spec field(s): {sorted(unknown)} "
                f"(expected a subset of {sorted(_SPEC_FIELDS)})"
            )
        kind = str(rec.get("kind", "pca") or "pca")
        if kind not in JOB_KINDS:
            raise ValueError(
                f"unknown job kind {kind!r} (expected one of "
                f"{list(JOB_KINDS)})"
            )
        if kind == "pairhmm":
            misapplied = [f for f in _PCA_ONLY_FIELDS if f in rec]
            if misapplied:
                raise ValueError(
                    f"spec field(s) {misapplied} do not apply to a "
                    "pairhmm job (reads are selected by references + "
                    "read_group_set_id)"
                )
        rgsid = rec.get("read_group_set_id")
        if rgsid is not None:
            if kind != "pairhmm":
                raise ValueError(
                    "read_group_set_id applies only to pairhmm jobs"
                )
            if not isinstance(rgsid, str) or not rgsid:
                raise ValueError(
                    "read_group_set_id must be a non-empty string"
                )
        vsids = rec.get("variant_set_ids")
        if vsids is None:
            one = rec.get("variant_set_id")
            vsids = [one] if one else []
        if not isinstance(vsids, (list, tuple)) or not all(
            isinstance(v, str) and v for v in vsids
        ):
            raise ValueError("variant_set_ids must be non-empty strings")
        af = rec.get("min_allele_frequency")
        if af is not None:
            af = float(af)
            if not (0.0 <= af <= 1.0):
                raise ValueError("min_allele_frequency must be in [0, 1]")
        num_pc = rec.get("num_pc")
        if num_pc is not None:
            num_pc = int(num_pc)
            if num_pc < 1:
                raise ValueError(f"num_pc must be >= 1, got {num_pc}")
        priority = int(rec.get("priority", 0))
        if not (-10 <= priority <= 10):
            # Priority is a cooperative nudge between trusted clients,
            # not a bidding war: an unbounded value would let one
            # tenant park above everyone else forever (the per-tenant
            # quota bounds volume, not position).
            raise ValueError(
                f"priority must be in [-10, 10], got {priority}"
            )
        refs = rec.get("references")
        if refs is not None and not isinstance(refs, str):
            raise ValueError("references must be a string")
        pca_mode = rec.get("pca_mode")
        if pca_mode is not None:
            from spark_examples_tpu.utils.config import PCA_MODES

            if pca_mode not in PCA_MODES:
                raise ValueError(
                    f"unknown pca_mode {pca_mode!r} (expected one of "
                    f"{list(PCA_MODES)})"
                )
        all_refs = rec.get("all_references")
        return cls(
            tenant=str(rec.get("tenant", "anonymous")) or "anonymous",
            variant_set_ids=tuple(vsids),
            references=refs,
            all_references=(
                None if all_refs is None else bool(all_refs)
            ),
            min_allele_frequency=af,
            num_pc=num_pc,
            priority=priority,
            samples=_sample_list(rec, "samples"),
            exclude_samples=_sample_list(rec, "exclude_samples"),
            pca_mode=pca_mode,
            kind=kind,
            read_group_set_id=rgsid,
        )

    def to_record(self) -> Dict[str, Any]:
        if self.kind == "pairhmm":
            # Only the read-side fields: a record carrying the (inert)
            # variant-side keys would be rejected by from_record's own
            # misapplied-field validation on journal replay.
            slim: Dict[str, Any] = {
                "kind": self.kind,
                "tenant": self.tenant,
                "references": self.references,
                "priority": self.priority,
            }
            if self.read_group_set_id is not None:
                slim["read_group_set_id"] = self.read_group_set_id
            return slim
        rec: Dict[str, Any] = {
            "tenant": self.tenant,
            "variant_set_ids": list(self.variant_set_ids),
            "references": self.references,
            "all_references": self.all_references,
            "min_allele_frequency": self.min_allele_frequency,
            "num_pc": self.num_pc,
            "priority": self.priority,
        }
        # Omitted when unset: journals written before the sample-
        # restriction fields existed replay unchanged, and unrestricted
        # specs keep their historical record shape.
        if self.samples is not None:
            rec["samples"] = list(self.samples)
        if self.exclude_samples is not None:
            rec["exclude_samples"] = list(self.exclude_samples)
        # Omitted when unset, like the restriction fields: pre-sketch
        # journals replay unchanged.
        if self.pca_mode is not None:
            rec["pca_mode"] = self.pca_mode
        # No "kind" key on the default kind: pre-kind journals and
        # their replayed record shapes stay byte-for-byte what round 12
        # wrote (and their cohort keys stay identical).
        return rec


def resolve_spec(spec: JobSpec, base: Any) -> Dict[str, Any]:
    """The spec with server defaults applied — the EXACT parameter set a
    job will run with, which is therefore what the cohort key must
    cover (``base`` is the server's PcaConfig template).

    A pairhmm job resolves to the read-side parameter set: the region,
    the readset filter, and every server knob that changes a score
    (consensus context, gap penalties, and the shard size — consensus
    haplotypes are voted per shard window, so partitioning is part of
    the result's identity). PCA jobs keep their historical record shape
    exactly (no ``kind`` key), so pre-kind journals and caches resolve
    to the same keys they always did.
    """
    if spec.kind == "pairhmm":
        return {
            "kind": "pairhmm",
            "references": (
                spec.references
                if spec.references is not None
                else base.references
            ),
            "read_group_set_id": (
                spec.read_group_set_id
                if spec.read_group_set_id is not None
                else getattr(base, "read_group_set_id", None)
            ),
            "bases_per_partition": int(base.bases_per_partition),
            "pairhmm_context": int(base.pairhmm_context),
            "pairhmm_gap_open_phred": float(
                base.pairhmm_gap_open_phred
            ),
            "pairhmm_gap_ext_phred": float(base.pairhmm_gap_ext_phred),
        }
    resolved_mode = (
        spec.pca_mode
        if spec.pca_mode is not None
        else getattr(base, "pca_mode", "auto")
    )
    out = {
        "variant_set_ids": list(
            spec.variant_set_ids or base.variant_set_ids
        ),
        "references": (
            spec.references
            if spec.references is not None
            else base.references
        ),
        "all_references": (
            spec.all_references
            if spec.all_references is not None
            else bool(base.all_references)
        ),
        "min_allele_frequency": (
            spec.min_allele_frequency
            if spec.min_allele_frequency is not None
            else base.min_allele_frequency
        ),
        "num_pc": (
            spec.num_pc if spec.num_pc is not None else base.num_pc
        ),
        "samples": _resolved_samples(spec.samples, base, "samples"),
        "exclude_samples": _resolved_samples(
            spec.exclude_samples, base, "exclude_samples"
        ),
    }
    if resolved_mode == "sketch":
        # Every EXACT engine is bit-identical on the same cohort, so
        # pca_mode has never been part of the resolved identity (and
        # pre-sketch journals/caches keep their keys). The sketch
        # engine is approximate and seeded — a sketch job's result is
        # a different artifact from the exact result AND from other
        # sketch parameterizations, so all of its knobs join the key.
        out["pca_mode"] = "sketch"
        out["sketch_oversample"] = int(
            getattr(base, "sketch_oversample", 8)
        )
        out["sketch_seed"] = int(getattr(base, "sketch_seed", 0))
        out["sketch_power_iters"] = int(
            getattr(base, "sketch_power_iters", 0)
        )
    return out


def _resolved_samples(
    spec_val: Optional[Tuple[str, ...]], base: Any, field: str
) -> Optional[List[str]]:
    """Spec value wins; otherwise the server default, canonicalized the
    same way (sorted, deduplicated) so key equality is frame equality."""
    if spec_val is not None:
        return list(spec_val)
    base_val = getattr(base, field, None)
    if not base_val:
        return None
    return sorted(set(base_val))


def cohort_key(spec: JobSpec, base: Any) -> str:
    """Hex result-cache key: murmur3_x64_128 over the canonical JSON of
    the resolved analysis parameters. Tenant and priority are excluded
    ON PURPOSE — identical analyses share results across tenants (the
    whole point of the cache)."""
    from spark_examples_tpu.genomics.hashing import murmur3_x64_128

    payload = json.dumps(
        resolve_spec(spec, base), sort_keys=True, separators=(",", ":")
    ).encode("utf-8")
    return murmur3_x64_128(payload).hex()


def job_config(
    spec: JobSpec, base: Any, checkpoint_dir: Optional[str] = None
) -> Any:
    """Per-job PcaConfig: the server template with the spec's analysis
    parameters applied and every emission/telemetry output stripped
    (jobs return rows; they never write the operator's artifacts)."""
    import dataclasses

    resolved = resolve_spec(spec, base)
    if spec.kind == "pairhmm":
        return dataclasses.replace(
            base,
            references=resolved["references"],
            read_group_set_id=resolved["read_group_set_id"],
            checkpoint_dir=None,
            elastic_checkpoint=False,
            output_path=None,
            trace_dir=None,
            trace_out=None,
            metrics_out=None,
            manifest_out=None,
        )
    pca_mode = (
        spec.pca_mode
        if spec.pca_mode is not None
        else getattr(base, "pca_mode", "auto")
    )
    if pca_mode == "sketch":
        # The sketch driver refuses checkpointed ingest (no snapshot
        # grid for a partial panel) — never hand it one.
        checkpoint_dir = None
    return dataclasses.replace(
        base,
        variant_set_ids=resolved["variant_set_ids"],
        references=resolved["references"],
        all_references=resolved["all_references"],
        min_allele_frequency=resolved["min_allele_frequency"],
        num_pc=resolved["num_pc"],
        samples=resolved["samples"],
        exclude_samples=resolved["exclude_samples"],
        pca_mode=pca_mode,
        checkpoint_dir=checkpoint_dir,
        elastic_checkpoint=False,
        output_path=None,
        trace_dir=None,
        trace_out=None,
        metrics_out=None,
        manifest_out=None,
    )


@dataclass
class Job:
    """One admitted submission's lifecycle (in-memory view; the journal
    is the durable truth)."""

    id: str
    spec: JobSpec
    key: str
    seq: int
    state: str = JOB_QUEUED
    cached: bool = False
    error: Optional[str] = None
    # Row shape is per-kind: (name, pc1, pc2, dataset) for pca,
    # (name, loglik, bucket) for pairhmm.
    result: Optional[List[Tuple[Any, ...]]] = None
    submitted_unix: float = field(default_factory=time.time)
    # Minted at admission, carried through journal -> replay -> every
    # span the job's execution emits (a tracer context field). None on
    # synthetic cache-hit views (no execution, no timeline).
    trace_id: Optional[str] = None

    def to_record(self, include_result: bool = True) -> Dict[str, Any]:
        rec: Dict[str, Any] = {
            "id": self.id,
            "state": self.state,
            "tenant": self.spec.tenant,
            "cached": self.cached,
            "submitted_unix": self.submitted_unix,
            "spec": self.spec.to_record(),
        }
        if self.trace_id is not None:
            rec["trace_id"] = self.trace_id
        if self.error is not None:
            rec["error"] = self.error
        if include_result and self.result is not None:
            rec["result"] = [list(row) for row in self.result]
        return rec


class JobJournal:
    """Append-only JSONL event log — the tier's crash-safe state.

    Every append is flushed to the OS immediately; the watchdog's
    pre-exit flush hook (``utils/watchdog.py``) additionally fsyncs it
    on the exit-77 fail-stop path, so a collective-timeout kill leaves
    the journal as durable as a clean shutdown. The ``serving.journal.
    append`` fault seam injects torn/error writes for the chaos suite.
    """

    def __init__(self, directory: str) -> None:
        os.makedirs(directory, exist_ok=True)
        self.directory = directory
        self.path = os.path.join(directory, _JOURNAL_NAME)
        self._lock = threading.Lock()
        self._f = open(self.path, "ab")
        # Heal a crash-torn tail BEFORE the first append: a kill mid-
        # write leaves a partial line with no newline, and appending
        # straight after it would merge the next (acknowledged!) event
        # into one unparseable line — silently destroying it on every
        # later replay. Terminating the torn bytes keeps them an
        # isolated skip-with-warning line, exactly what replay expects.
        if self._f.tell() > 0:
            with open(self.path, "rb") as probe:
                probe.seek(-1, os.SEEK_END)
                if probe.read(1) != b"\n":
                    self._f.write(b"\n")
                    self._f.flush()
        from spark_examples_tpu.utils.watchdog import register_flush_hook

        self._hook_name = f"job-journal:{self.path}"
        register_flush_hook(self._hook_name, self.flush)

    def append(self, event: Dict[str, Any]) -> None:
        from spark_examples_tpu.resilience import faults

        line = (
            json.dumps(event, separators=(",", ":")) + "\n"
        ).encode("utf-8")
        with self._lock:
            rule = faults.take(
                "serving.journal.append", key=str(event.get("e", ""))
            )
            if rule is not None and rule.kind == "torn":
                # A torn append: half the bytes, no newline — exactly
                # what a SIGKILL mid-write leaves. The replay loader
                # must skip it; the NEXT append would corrupt it
                # further, so a torn rule models the crash-final write.
                self._f.write(line[: max(1, len(line) // 2)])
                self._f.flush()
                return
            if rule is not None:
                raise faults.InjectedFault(
                    "serving.journal.append", rule.kind, self.path,
                    rule.message,
                )
            self._f.write(line)
            self._f.flush()

    def flush(self) -> None:
        """Flush + fsync (the watchdog pre-exit hook target).

        Bounded lock wait: this runs on the fail-stop path, where a
        writer wedged inside an append (hung NFS — exactly the kind of
        stall that fired the watchdog) may hold the lock forever. The
        exit-77 guarantee outranks the fsync: give up after 2 s rather
        than convert fail-stop into a permanent hang. (The fsync itself
        can also wedge on hung storage; the watchdog bounds the whole
        hook pass with a daemon-thread deadline for that case.)
        """
        if not self._lock.acquire(timeout=2.0):
            return
        try:
            if self._f.closed:
                return
            self._f.flush()
            os.fsync(self._f.fileno())
        finally:
            self._lock.release()

    def probe(self, timeout_s: float = 0.5) -> bool:
        """Bounded writability probe (the ``/healthz`` journal check):
        True when the journal file is open and flushable. Same bounded-
        wait discipline as :meth:`flush` — a probe that hangs on the
        wedged writer it exists to detect is worse than useless."""
        if not self._lock.acquire(timeout=max(0.0, timeout_s)):
            return False
        try:
            if self._f.closed:
                return False
            self._f.flush()
            return True
        except OSError:
            return False
        finally:
            self._lock.release()

    def close(self) -> None:
        from spark_examples_tpu.utils.watchdog import unregister_flush_hook

        unregister_flush_hook(self._hook_name)
        with self._lock:
            if not self._f.closed:
                self._f.flush()
                self._f.close()

    @staticmethod
    def replay_events(directory: str) -> Iterator[Dict[str, Any]]:
        """Parsed journal events in append order; unparseable lines (a
        torn tail) are warned about and skipped — resume must degrade
        to re-running, never die on its own safety net."""
        path = os.path.join(directory, _JOURNAL_NAME)
        if not os.path.exists(path):
            return
        with open(path, "rb") as f:
            for lineno, raw in enumerate(f, 1):
                raw = raw.strip()
                if not raw:
                    continue
                try:
                    doc = json.loads(raw)
                except ValueError:
                    print(
                        f"WARNING: skipping torn/corrupt journal line "
                        f"{path}:{lineno} ({len(raw)} bytes) — jobs it "
                        "described re-run from their last durable event.",
                        file=sys.stderr,
                    )
                    continue
                if isinstance(doc, dict):
                    yield doc
