"""spark_examples_tpu — a TPU-native framework for population-scale genomics.

A ground-up JAX/XLA/pjit re-design with the capabilities of the reference
``googlegenomics/spark-examples`` stack: streaming variant/read ingest over
sharded genomic ranges, the search/pileup/coverage example drivers, and the
``VariantsPcaDriver`` principal-coordinate (PCoA) pipeline — genotype blocks
streamed into sharded ``jax.Array``s, ``jnp.einsum`` + ``jnp.linalg.eigh``
under ``pjit`` over ICI/DCN instead of Spark shuffle + Breeze/MLlib on a
driver JVM.

Layer map (mirrors SURVEY.md §1, re-architected TPU-first):

- :mod:`spark_examples_tpu.genomics` — host data plane: typed records, shard
  manifests, sources, callset index (replaces the reference's L1/L2 client +
  custom RDD layer).
- :mod:`spark_examples_tpu.arrays`  — ingest→device: dense genotype blocks,
  double-buffered feeds.
- :mod:`spark_examples_tpu.ops`     — device math under ``jit``: Gramian,
  double-centering, PCoA/eig, reads kernels.
- :mod:`spark_examples_tpu.parallel`— mesh + collectives: pjit shardings,
  blockwise variant-axis streaming, multi-host init.
- :mod:`spark_examples_tpu.models`  — the pipelines ("apps"): PCA driver and
  the search-variants / search-reads examples (replaces the reference L3).
- :mod:`spark_examples_tpu.utils`   — config/flags, IO stats, checkpointing,
  logging.
- :mod:`spark_examples_tpu.cli`     — command-line entry points.
- :mod:`spark_examples_tpu.bridge`  — the PcaBackend seam so external drivers
  can delegate the dense math.
"""

__version__ = "0.1.0"
