"""Local-shared-directory backend for :class:`DurableStore`.

One directory (an NFS mount, a shared volume, a tmpdir under test) is
the whole store: blobs live under ``objects/``, lease documents under
``leases/``. Every mutation follows the repo's atomic-write idiom —
tmp→flush→fsync→rename — so a kill -9 at any instant leaves either the
old bytes or the new bytes, never a torn blob; every blob carries an
embedded blake2b digest so a reader can never consume silent
corruption (:class:`StoreCorruptError` is loud).

Compare-and-swap for leases is built on the only cross-process atomic
primitive a plain directory offers: ``os.mkdir`` of a per-lease mutex
directory. The mutex is held for microseconds (one read-modify-write of
a <1 KiB JSON doc); a holder that died mid-CAS is broken after
``_LEASE_MUTEX_STALE_S``. Fencing tokens are monotonic across ALL
acquisitions of a lease name — first grab, re-grab after expiry,
takeover — so a write fenced on an old token can always be rejected.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

from spark_examples_tpu.resilience import faults
from spark_examples_tpu.utils.lockcheck import assert_lock_held

__all__ = [
    "DurableStore",
    "FencedWriteError",
    "Lease",
    "LocalDirStore",
    "StoreCorruptError",
    "StoreError",
]

_MAGIC = b"SESTORE1"
# A crashed CAS holder is broken after this long — the mutex protects a
# sub-millisecond read-modify-write, so seconds of silence means death.
_LEASE_MUTEX_STALE_S = 5.0
_LEASE_MUTEX_WAIT_S = 2.0


class StoreError(IOError):
    """The store is unreachable or an operation failed as IO weather —
    the degradable shape: callers drop to single-replica local mode."""


class StoreCorruptError(StoreError):
    """A blob's embedded checksum does not match its payload."""


class FencedWriteError(RuntimeError):
    """A lease-fenced operation was rejected: the caller's fencing
    token is stale (a peer took the lease over, or it expired and was
    re-acquired). Deliberately NOT an ``IOError`` — retry/degrade
    handlers for IO weather must never swallow a fencing rejection."""


@dataclass(frozen=True)
class Lease:
    """One lease observation: who holds ``name``, under which fencing
    ``token``, until ``expires_unix`` (per the store's clock)."""

    name: str
    owner: str
    token: int
    expires_unix: float

    def expired(self, now: float) -> bool:
        return now >= self.expires_unix


class DurableStore:
    """The durable-state contract the replica plane is written against.

    Blob half: ``put`` is atomic and checksummed, ``get`` verifies,
    ``list_keys`` enumerates by prefix. Lease half: ``lease_acquire`` /
    ``lease_renew`` / ``lease_release`` are compare-and-swap on a
    per-name lease document carrying a monotonic fencing token;
    ``check_fence`` / ``put_fenced`` reject stale-token writers loudly.
    """

    def put(self, key: str, data: bytes) -> None:
        raise NotImplementedError

    def get(self, key: str) -> bytes:
        raise NotImplementedError

    def list_keys(self, prefix: str = "") -> List[str]:
        raise NotImplementedError

    def delete(self, key: str) -> None:
        raise NotImplementedError

    def lease_acquire(
        self, name: str, owner: str, ttl_s: float
    ) -> Optional[Lease]:
        raise NotImplementedError

    def lease_renew(self, lease: Lease, ttl_s: float) -> Lease:
        raise NotImplementedError

    def lease_release(self, lease: Lease) -> None:
        raise NotImplementedError

    def lease_get(self, name: str) -> Optional[Lease]:
        raise NotImplementedError

    def lease_list(self, prefix: str = "") -> List[Lease]:
        raise NotImplementedError

    def check_fence(self, lease: Lease) -> None:
        raise NotImplementedError

    def put_fenced(self, key: str, data: bytes, lease: Lease) -> None:
        raise NotImplementedError


def _digest(payload: bytes) -> str:
    return hashlib.blake2b(payload, digest_size=16).hexdigest()


class LocalDirStore(DurableStore):
    """:class:`DurableStore` over one shared directory."""

    def __init__(
        self,
        root: str,
        clock: Callable[[], float] = time.time,
    ) -> None:
        self.root = os.path.abspath(root)
        self._clock = clock
        self._lock = threading.Lock()
        # Op counters for /statusz introspection; guarded by _lock.
        self._op_counts: Dict[str, int] = {}
        try:
            os.makedirs(os.path.join(self.root, "objects"), exist_ok=True)
            os.makedirs(os.path.join(self.root, "leases"), exist_ok=True)
        except OSError as e:
            raise StoreError(f"store root {self.root!r} unusable: {e}") from e

    # -- introspection ---------------------------------------------------------

    def _count_locked(self, op: str) -> None:
        assert_lock_held(self._lock, "LocalDirStore._count_locked")
        self._op_counts[op] = self._op_counts.get(op, 0) + 1

    def _count(self, op: str) -> None:
        with self._lock:
            self._count_locked(op)

    def op_counts(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._op_counts)

    # -- paths -----------------------------------------------------------------

    def _object_path(self, key: str) -> str:
        if not key or key.startswith(("/", "\\")) or ".." in key.split("/"):
            raise ValueError(f"invalid store key {key!r}")
        return os.path.join(self.root, "objects", *key.split("/"))

    def _lease_path(self, name: str) -> str:
        if not name or "/" in name or name.startswith("."):
            raise ValueError(f"invalid lease name {name!r}")
        return os.path.join(self.root, "leases", name + ".json")

    # -- blobs -----------------------------------------------------------------

    def put(self, key: str, data: bytes) -> None:
        """Atomic checksummed write: tmp→flush→fsync→rename. The
        ``store.write`` seam fires between the tmp write and the
        rename — a ``torn`` fault truncates the tmp and raises, so a
        partial can only ever exist under a ``*.tmp-*`` name."""
        path = self._object_path(key)
        self._count("put")
        try:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            tmp = f"{path}.tmp-{os.getpid()}"
            framed = (
                _MAGIC
                + b" "
                + _digest(data).encode("ascii")
                + b" "
                + str(len(data)).encode("ascii")
                + b"\n"
                + data
            )
            with open(tmp, "wb") as f:
                f.write(framed)
                f.flush()
                os.fsync(f.fileno())
            # Torn truncates the tmp and raises — the kill -9-mid-write
            # shape: the partial only ever exists under a *.tmp-* name
            # (ignored by get/list), the rename never runs.
            faults.inject_write("store.write", tmp)
            os.replace(tmp, path)
        except faults.InjectedFault as e:
            raise StoreError(f"store put {key!r} failed: {e}") from e
        except OSError as e:
            raise StoreError(f"store put {key!r} failed: {e}") from e

    def get(self, key: str) -> bytes:
        """Checksummed read; :class:`KeyError` when absent,
        :class:`StoreCorruptError` when the digest does not match."""
        path = self._object_path(key)
        self._count("get")
        try:
            faults.inject("store.read", key=key)
            with open(path, "rb") as f:
                blob = f.read()
        except FileNotFoundError:
            raise KeyError(key) from None
        except faults.InjectedFault as e:
            raise StoreError(f"store get {key!r} failed: {e}") from e
        except OSError as e:
            raise StoreError(f"store get {key!r} failed: {e}") from e
        header, sep, payload = blob.partition(b"\n")
        parts = header.split(b" ")
        if not sep or len(parts) != 3 or parts[0] != _MAGIC:
            raise StoreCorruptError(f"store blob {key!r}: unframed/torn")
        if (
            str(len(payload)).encode("ascii") != parts[2]
            or _digest(payload).encode("ascii") != parts[1]
        ):
            raise StoreCorruptError(
                f"store blob {key!r}: checksum mismatch "
                "(torn or corrupted write)"
            )
        return payload

    def list_keys(self, prefix: str = "") -> List[str]:
        base = os.path.join(self.root, "objects")
        self._count("list")
        out: List[str] = []
        try:
            for dirpath, _dirnames, filenames in os.walk(base):
                for fname in filenames:
                    if ".tmp-" in fname:
                        continue
                    rel = os.path.relpath(
                        os.path.join(dirpath, fname), base
                    ).replace(os.sep, "/")
                    if rel.startswith(prefix):
                        out.append(rel)
        except OSError as e:
            raise StoreError(f"store list {prefix!r} failed: {e}") from e
        return sorted(out)

    def delete(self, key: str) -> None:
        self._count("delete")
        try:
            os.unlink(self._object_path(key))
        except FileNotFoundError:
            pass
        except OSError as e:
            raise StoreError(f"store delete {key!r} failed: {e}") from e

    # -- lease CAS -------------------------------------------------------------

    def _lease_fault(self, op: str, name: str) -> None:
        """The ``store.lease`` seam. Kinds are interpreted at the CAS:
        ``error`` raises :class:`StoreError` (store unreachable),
        ``stall`` sleeps, and ``corrupt`` is the **stale-token** shape —
        the CAS behaves as though a peer bumped the fencing token, so
        the caller's lease is rejected as lost."""
        rule = faults.take("store.lease", key=f"{op}:{name}")
        if rule is None:
            return
        if rule.kind == "stall":
            time.sleep(rule.stall_s)
            return
        if rule.kind == "corrupt":
            raise FencedWriteError(
                f"lease {name!r} {op} rejected: stale fencing token "
                "(injected)"
            )
        raise StoreError(f"store lease {op} {name!r} failed: injected fault")

    def _mutex_acquire(self, name: str) -> str:
        lock_dir = self._lease_path(name) + ".lck"
        deadline = time.monotonic() + _LEASE_MUTEX_WAIT_S
        while True:
            try:
                os.mkdir(lock_dir)
                return lock_dir
            except FileExistsError:
                try:
                    age = time.time() - os.stat(lock_dir).st_mtime
                    if age > _LEASE_MUTEX_STALE_S:
                        # Crashed CAS holder: break the mutex loudly.
                        print(
                            f"[store] breaking stale lease mutex {lock_dir}"
                            f" (held {age:.1f}s)"
                        )
                        os.rmdir(lock_dir)
                        continue
                except OSError:
                    pass
                if time.monotonic() > deadline:
                    raise StoreError(
                        f"lease mutex {lock_dir} held too long"
                    ) from None
                time.sleep(0.005)
            except OSError as e:
                raise StoreError(f"lease mutex {lock_dir}: {e}") from e

    def _mutex_release(self, lock_dir: str) -> None:
        try:
            os.rmdir(lock_dir)
        except OSError:
            pass

    def _read_lease_doc(self, name: str) -> Optional[Dict[str, object]]:
        try:
            with open(self._lease_path(name), "rb") as f:
                doc = json.loads(f.read().decode("utf-8"))
            return doc if isinstance(doc, dict) else None
        except FileNotFoundError:
            return None
        except (OSError, ValueError):
            # A torn lease doc reads as "no lease": the next CAS
            # rewrites it atomically with the preserved token floor.
            return None

    def _write_lease_doc(self, name: str, doc: Dict[str, object]) -> None:
        path = self._lease_path(name)
        tmp = f"{path}.tmp-{os.getpid()}"
        try:
            with open(tmp, "wb") as f:
                f.write(json.dumps(doc, sort_keys=True).encode("utf-8"))
                f.flush()
                os.fsync(f.fileno())
                # Torn-write seam: a lease doc killed mid-write must
                # read back as "no lease" with the token floor intact.
                faults.inject_write("store.lease.write", tmp)
            os.replace(tmp, path)
        except OSError as e:
            raise StoreError(f"lease write {name!r} failed: {e}") from e

    def _lease_of(self, doc: Dict[str, object]) -> Lease:
        return Lease(
            name=str(doc["name"]),
            owner=str(doc["owner"]),
            token=int(doc["token"]),  # type: ignore[arg-type]
            expires_unix=float(doc["expires_unix"]),  # type: ignore[arg-type]
        )

    def lease_acquire(
        self, name: str, owner: str, ttl_s: float
    ) -> Optional[Lease]:
        """CAS acquire: succeeds when the lease is free, expired, or a
        takeover target — every success bumps the monotonic fencing
        token, so the previous holder's token is stale the instant this
        returns. ``None`` when a live peer holds it."""
        self._count("lease")
        self._lease_fault("acquire", name)
        mutex = self._mutex_acquire(name)
        try:
            now = self._clock()
            doc = self._read_lease_doc(name)
            token = 0
            if doc is not None:
                held = self._lease_of(doc)
                token = held.token
                if held.owner != owner and not held.expired(now):
                    return None
            new = Lease(
                name=name,
                owner=owner,
                token=token + 1,
                expires_unix=now + ttl_s,
            )
            self._write_lease_doc(
                name,
                {
                    "name": new.name,
                    "owner": new.owner,
                    "token": new.token,
                    "expires_unix": new.expires_unix,
                },
            )
            return new
        finally:
            self._mutex_release(mutex)

    def lease_renew(self, lease: Lease, ttl_s: float) -> Lease:
        """CAS renew: extends the TTL only while ``lease`` is still the
        current (owner, token); raises :class:`FencedWriteError` when
        the token moved on — the holder is a zombie."""
        self._count("lease")
        self._lease_fault("renew", lease.name)
        mutex = self._mutex_acquire(lease.name)
        try:
            doc = self._read_lease_doc(lease.name)
            if doc is None:
                raise FencedWriteError(
                    f"lease {lease.name!r} renew rejected: lease gone"
                )
            held = self._lease_of(doc)
            if held.owner != lease.owner or held.token != lease.token:
                raise FencedWriteError(
                    f"lease {lease.name!r} renew rejected: fencing token "
                    f"{lease.token} is stale (current: {held.token} held "
                    f"by {held.owner!r})"
                )
            new = Lease(
                name=lease.name,
                owner=lease.owner,
                token=lease.token,
                expires_unix=self._clock() + ttl_s,
            )
            self._write_lease_doc(
                lease.name,
                {
                    "name": new.name,
                    "owner": new.owner,
                    "token": new.token,
                    "expires_unix": new.expires_unix,
                },
            )
            return new
        finally:
            self._mutex_release(mutex)

    def lease_release(self, lease: Lease) -> None:
        """CAS release: deletes the doc only while still the current
        (owner, token); a stale releaser is a silent no-op — the lease
        already belongs to someone else."""
        self._count("lease")
        self._lease_fault("release", lease.name)
        mutex = self._mutex_acquire(lease.name)
        try:
            doc = self._read_lease_doc(lease.name)
            if doc is None:
                return
            held = self._lease_of(doc)
            if held.owner == lease.owner and held.token == lease.token:
                try:
                    os.unlink(self._lease_path(lease.name))
                except OSError:
                    pass
        finally:
            self._mutex_release(mutex)

    def lease_get(self, name: str) -> Optional[Lease]:
        self._count("lease")
        doc = self._read_lease_doc(name)
        return None if doc is None else self._lease_of(doc)

    def lease_list(self, prefix: str = "") -> List[Lease]:
        self._count("lease")
        base = os.path.join(self.root, "leases")
        out: List[Lease] = []
        try:
            names = sorted(os.listdir(base))
        except OSError as e:
            raise StoreError(f"lease list failed: {e}") from e
        for fname in names:
            if not fname.endswith(".json") or ".tmp-" in fname:
                continue
            name = fname[: -len(".json")]
            if not name.startswith(prefix):
                continue
            doc = self._read_lease_doc(name)
            if doc is not None:
                out.append(self._lease_of(doc))
        return out

    def now(self) -> float:
        """The store's clock — lease expiry is judged against THIS
        clock, so every replica on the shared directory agrees."""
        return self._clock()

    # -- fencing ---------------------------------------------------------------

    def check_fence(self, lease: Lease) -> None:
        """Reject a stale-token caller loudly. Raises
        :class:`FencedWriteError` when ``lease`` is no longer the
        current (owner, token) or has expired."""
        self._lease_fault("check", lease.name)
        doc = self._read_lease_doc(lease.name)
        if doc is None:
            raise FencedWriteError(
                f"fenced write rejected: lease {lease.name!r} is gone"
            )
        held = self._lease_of(doc)
        if held.owner != lease.owner or held.token != lease.token:
            raise FencedWriteError(
                f"fenced write rejected: token {lease.token} of "
                f"{lease.owner!r} is stale (lease {lease.name!r} now "
                f"token {held.token} held by {held.owner!r})"
            )
        if held.expired(self._clock()):
            raise FencedWriteError(
                f"fenced write rejected: lease {lease.name!r} of "
                f"{lease.owner!r} expired and was never renewed"
            )

    def put_fenced(self, key: str, data: bytes, lease: Lease) -> None:
        """Fence-checked atomic put: the check and the write happen
        under the lease's CAS mutex, so a takeover (which bumps the
        token under the same mutex) strictly orders against it — a
        zombie's write is either rejected here or completed before the
        takeover began, never interleaved."""
        mutex = self._mutex_acquire(lease.name)
        try:
            self.check_fence(lease)
            self.put(key, data)
        finally:
            self._mutex_release(mutex)
