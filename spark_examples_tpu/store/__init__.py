"""Durable shared state for the replicated serving plane.

The serving tier's crash safety (PR 6) assumed ONE process owning one
journal directory. The replica plane needs the same durability
*shared*: N ``serve-cohort`` processes over one store, any of which can
die at any instant, with job ownership handed around by leases instead
of by being the only process alive. This package is that seam:

- :class:`DurableStore` — the abstract contract: atomic checksummed
  blobs (``put`` is tmp→fsync→rename, ``get`` verifies the embedded
  digest and raises :class:`StoreCorruptError` loudly on mismatch),
  prefix listing, and compare-and-swap **lease** operations carrying
  monotonic fencing tokens;
- :class:`LocalDirStore` — the local-shared-directory backend (an NFS
  mount, a shared volume, or a tmpdir in tests). ROADMAP item 4's
  GCS/S3 backend plugs into the same contract later;
- the fencing-token discipline: every successful lease acquisition
  (first grab, re-grab after expiry, takeover from a dead peer) bumps a
  token that only ever grows. A replica that lost its lease holds a
  stale token, and every fenced write (:meth:`DurableStore.check_fence`
  before journal/result/cache writes) is rejected with
  :class:`FencedWriteError` — loudly, never torn-merged.

Chaos seams (``store.read`` / ``store.write`` / ``store.lease``) ride
the resilience FaultPlan like every other durability surface; see
``resilience/faults.py`` for the site table.
"""

from spark_examples_tpu.store.local import (
    Lease,
    DurableStore,
    FencedWriteError,
    LocalDirStore,
    StoreCorruptError,
    StoreError,
)

__all__ = [
    "DurableStore",
    "FencedWriteError",
    "Lease",
    "LocalDirStore",
    "StoreCorruptError",
    "StoreError",
]
