"""PcaBackend implementations + a newline-JSON TCP bridge.

Protocol (one JSON object per line, UTF-8):

    → {"cmd": "init", "n_samples": N, "num_pc": k}
    → {"cmd": "calls", "batch": [[s0, s1, ...], ...]}   (repeatable)
    → {"cmd": "finish"}
    ← {"coords": [[pc1, pc2, ...], ...], "eigvals": [...]}

Newline-JSON over a socket keeps the bridge dependency-free on both sides
(a JVM client needs ~20 lines; no protobuf/py4j/grpc pinning) while the
payload — integer index lists — is exactly the reference's
``RDD[Seq[Int]]`` stage boundary, so a Spark driver can ship partitions
straight through ``collect``-free ``foreachPartition`` writes.
"""

from __future__ import annotations

import json
import socket
import socketserver
import threading
from typing import Iterable, List, Optional, Protocol, Sequence

import numpy as np

__all__ = [
    "PcaBackend",
    "TpuPcaBackend",
    "PcaBridgeServer",
    "PcaBridgeClient",
    "iter_call_batches",
]


def iter_call_batches(
    calls: Iterable[Sequence[int]], batch_size: int
) -> Iterable[List[List[int]]]:
    """Group per-variant index lists into client-side wire batches —
    the one batching rule both bridge clients (newline-JSON TCP and
    gRPC ComputePca) share, so flush semantics can never diverge."""
    batch: List[List[int]] = []
    for c in calls:
        batch.append([int(i) for i in c])
        if len(batch) >= batch_size:
            yield batch
            batch = []
    if batch:
        yield batch


class PcaBackend(Protocol):
    """The seam: per-variant sample-index lists in, coordinates out."""

    def compute(
        self, calls: Iterable[Sequence[int]], n_samples: int, num_pc: int
    ): ...


class TpuPcaBackend:
    """In-process backend: blockwise Gramian + PCoA on the local device(s).

    The ``JaxTpuPcaBackend`` of the BASELINE north star; the counterpart
    ``SparkBreezePcaBackend`` is the reference's own driver-side math.
    """

    def __init__(self, mesh=None, block_variants: int = 8192):
        self.mesh = mesh
        self.block_variants = block_variants

    def compute(
        self, calls: Iterable[Sequence[int]], n_samples: int, num_pc: int
    ):
        if num_pc < 1 or n_samples < 1:
            raise ValueError(
                f"need n_samples >= 1 and num_pc >= 1, got "
                f"n_samples={n_samples}, num_pc={num_pc}"
            )
        from spark_examples_tpu.arrays.blocks import blocks_from_calls
        from spark_examples_tpu.ops import gramian_blockwise, pcoa

        blocks = blocks_from_calls(calls, n_samples, self.block_variants)
        if self.mesh is not None:
            from spark_examples_tpu.parallel.sharded import (
                sharded_gramian_blockwise,
                sharded_pcoa,
            )

            g = sharded_gramian_blockwise(blocks, n_samples, self.mesh)
            coords, eigvals = sharded_pcoa(g, num_pc, self.mesh)
        else:
            g = gramian_blockwise(blocks, n_samples)
            coords, eigvals = pcoa(g, num_pc)
        return np.asarray(coords), np.asarray(eigvals)


class _Handler(socketserver.StreamRequestHandler):
    def handle(self):
        backend: PcaBackend = self.server.backend  # type: ignore[attr-defined]
        n_samples = num_pc = None
        batches: List[List[int]] = []
        for raw in self.rfile:
            msg = json.loads(raw)
            cmd = msg.get("cmd")
            if cmd == "init":
                n_samples = int(msg["n_samples"])
                num_pc = int(msg["num_pc"])
            elif cmd == "calls":
                batches.extend(msg["batch"])
            elif cmd == "finish":
                if n_samples is None:
                    self._reply({"error": "finish before init"})
                    return
                try:
                    coords, eigvals = backend.compute(
                        iter(batches), n_samples, num_pc
                    )
                except (ValueError, KeyError) as e:
                    # Validation failures travel back to the client
                    # instead of silently dropping the connection.
                    self._reply({"error": str(e)})
                    return
                self._reply(
                    {
                        "coords": np.asarray(coords).tolist(),
                        "eigvals": np.asarray(eigvals).tolist(),
                    }
                )
                return
            else:
                self._reply({"error": f"unknown cmd {cmd!r}"})
                return

    def _reply(self, obj) -> None:
        self.wfile.write((json.dumps(obj) + "\n").encode())


class PcaBridgeServer:
    """Threaded TCP server wrapping any PcaBackend."""

    def __init__(self, backend: Optional[PcaBackend] = None, port: int = 0):
        self._srv = socketserver.ThreadingTCPServer(
            ("127.0.0.1", port), _Handler
        )
        self._srv.daemon_threads = True
        self._srv.backend = backend or TpuPcaBackend()  # type: ignore[attr-defined]
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._srv.server_address[1]

    def start(self) -> "PcaBridgeServer":
        self._thread = threading.Thread(
            target=self._srv.serve_forever, daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()


class PcaBridgeClient:
    """Reference client (the role the Scala driver's PcaBackend stub plays)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._sock = socket.create_connection((host, port))
        self._file = self._sock.makefile("rwb")

    def _send(self, obj) -> None:
        self._file.write((json.dumps(obj) + "\n").encode())
        self._file.flush()

    def compute(
        self,
        calls: Iterable[Sequence[int]],
        n_samples: int,
        num_pc: int,
        batch_size: int = 4096,
    ):
        self._send({"cmd": "init", "n_samples": n_samples, "num_pc": num_pc})
        for batch in iter_call_batches(calls, batch_size):
            self._send({"cmd": "calls", "batch": batch})
        self._send({"cmd": "finish"})
        resp = json.loads(self._file.readline())
        if "error" in resp:
            raise RuntimeError(resp["error"])
        return np.asarray(resp["coords"]), np.asarray(resp["eigvals"])

    def close(self) -> None:
        self._file.close()
        self._sock.close()
