"""The PcaBackend seam: delegate the dense math from any external driver.

The reference already factors its pipeline so the dense math is replaceable
— the PySpark twin drives the Scala ingest through py4j and hands row RDDs
back to the JVM for the eigendecomposition (``variants_pca.py:123-152``).
This package is that seam as a service: an external driver (the Scala
``VariantsPcaDriver``, or anything else) streams per-variant sample-index
lists — exactly the ``RDD[Seq[Int]]`` interface at
``VariantsPca.scala:153-168`` — and receives principal coordinates computed
on TPU.
"""

from spark_examples_tpu.bridge.backend import (
    PcaBackend,
    TpuPcaBackend,
    PcaBridgeServer,
    PcaBridgeClient,
)

__all__ = [
    "PcaBackend",
    "TpuPcaBackend",
    "PcaBridgeServer",
    "PcaBridgeClient",
]
