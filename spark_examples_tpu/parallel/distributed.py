"""Multi-host coordination over DCN — the jax.distributed layer.

The reference's driver⇄executor control plane (Spark master, task
scheduling, accumulator merging) maps onto ``jax.distributed``: one process
per host, ``jax.distributed.initialize`` over DCN, process 0 as the
"driver" for metadata/emission, and device collectives for anything
numeric. Host-side counters merge with an explicit all-reduce
(:func:`allreduce_host_stats`) — the accumulator story.

Single-host (including the one-chip bench and the CPU test mesh) is the
no-op fast path throughout: nothing here requires a cluster.
"""

from __future__ import annotations

import os
from typing import Optional

import jax
import numpy as np

from spark_examples_tpu.utils.stats import IoStats

__all__ = ["initialize_from_env", "is_coordinator", "allreduce_host_stats"]


def initialize_from_env() -> bool:
    """Initialize jax.distributed when a cluster env is present.

    Recognizes the standard coordinator variables (JAX_COORDINATOR_ADDRESS /
    num processes / process id, or cloud-TPU auto-detection via
    ``jax.distributed.initialize()`` no-arg form when
    ``SPARK_EXAMPLES_TPU_MULTIHOST=1``). Returns True if distributed mode
    was initialized.
    """
    if os.environ.get("JAX_COORDINATOR_ADDRESS"):
        jax.distributed.initialize(
            coordinator_address=os.environ["JAX_COORDINATOR_ADDRESS"],
            num_processes=int(os.environ["JAX_NUM_PROCESSES"]),
            process_id=int(os.environ["JAX_PROCESS_ID"]),
        )
        return True
    if os.environ.get("SPARK_EXAMPLES_TPU_MULTIHOST") == "1":
        jax.distributed.initialize()
        return True
    return False


def is_coordinator() -> bool:
    """Process 0 plays the reference's "driver" role (emission, metadata)."""
    return jax.process_index() == 0


def allreduce_host_stats(stats: IoStats) -> IoStats:
    """Merge per-host IoStats across processes into global totals.

    Single-process: identity. Multi-process: all-gather the counter vector
    through the devices (the accumulator merge the Spark driver did).
    """
    if jax.process_count() == 1:
        return stats
    from jax.experimental import multihost_utils

    vec = np.asarray(stats.as_vector(), dtype=np.int64)
    total = np.asarray(
        multihost_utils.process_allgather(vec)
    ).sum(axis=0)
    merged = IoStats()
    merged.add(
        partitions=int(total[0]),
        reference_bases=int(total[1]),
        requests=int(total[2]),
        unsuccessful_responses=int(total[3]),
        io_exceptions=int(total[4]),
        variants_read=int(total[5]),
        reads_read=int(total[6]),
    )
    return merged
