"""Multi-host coordination over DCN — the jax.distributed layer.

The reference's driver⇄executor control plane (Spark master, task
scheduling, accumulator merging) maps onto ``jax.distributed``: one process
per host, ``jax.distributed.initialize`` over DCN, process 0 as the
"driver" for metadata/emission, and device collectives for anything
numeric. Host-side counters merge with an explicit all-reduce
(:func:`allreduce_host_stats`) — the accumulator story.

Single-host (including the one-chip bench and the CPU test mesh) is the
no-op fast path throughout: nothing here requires a cluster.
"""

from __future__ import annotations

import os

import jax
import numpy as np

from spark_examples_tpu.utils.stats import IoStats

__all__ = [
    "initialize_from_env",
    "is_coordinator",
    "allreduce_host_stats",
    "allreduce_gramian",
]


def _enable_cpu_collectives() -> None:
    """Select a cross-process CPU collectives implementation (gloo).

    A multi-process CPU mesh (the pod-sim test/bench/CI shape, and any
    DCN-only deployment) needs a collectives backend compiled into the
    CPU client; without one every cross-process program dies with
    "Multiprocess computations aren't implemented on the CPU backend".
    Must run BEFORE the backend client is created, which is why it sits
    inside :func:`initialize_from_env` next to the distributed init.
    Harmless for TPU pods (it only configures the host CPU client) and
    a silent no-op on jax builds without the knob.
    """
    try:
        from jax._src import xla_bridge  # noqa: F401  (defines the flag)

        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:  # pragma: no cover — jax spelling drift
        pass


def initialize_from_env() -> bool:
    """Initialize jax.distributed when a cluster env is present.

    Recognizes the standard coordinator variables (JAX_COORDINATOR_ADDRESS /
    num processes / process id, or cloud-TPU auto-detection via
    ``jax.distributed.initialize()`` no-arg form when
    ``SPARK_EXAMPLES_TPU_MULTIHOST=1``). Returns True if distributed mode
    was initialized.
    """
    if os.environ.get("JAX_COORDINATOR_ADDRESS"):
        _enable_cpu_collectives()
        jax.distributed.initialize(
            coordinator_address=os.environ["JAX_COORDINATOR_ADDRESS"],
            num_processes=int(os.environ["JAX_NUM_PROCESSES"]),
            process_id=int(os.environ["JAX_PROCESS_ID"]),
        )
        return True
    if os.environ.get("SPARK_EXAMPLES_TPU_MULTIHOST") == "1":
        _enable_cpu_collectives()
        jax.distributed.initialize()
        return True
    return False


def is_coordinator() -> bool:
    """Process 0 plays the reference's "driver" role (emission, metadata)."""
    return jax.process_index() == 0


def allreduce_gramian(g_local, chunk_bytes: int = 64 << 20):
    """Sum per-host partial Gramians into the global G.

    The multi-host data-parallel reduction: each host ingests a disjoint
    slice of the shard manifest and accumulates its own partial
    ``G_h = X_h @ X_h.T``; the global Gramian is ``Σ_h G_h`` (the
    ``reduceByKey`` across executors of VariantsPca.scala:190, but an
    all-reduce over DCN instead of an N²-entry shuffle). Single-process:
    identity.

    The reduction runs in row chunks so transient memory is bounded by
    ``process_count × chunk_bytes`` instead of ``process_count`` full
    copies of G (which at the 100k-sample stress scale would be hundreds
    of GB per host).
    """
    if jax.process_count() == 1:
        return g_local
    import jax.numpy as jnp
    from jax.experimental import multihost_utils

    from spark_examples_tpu import obs

    if not getattr(g_local, "is_fully_addressable", True):
        # In this framework a process-spanning array can only come from the
        # global-mesh accumulators (gramian_blockwise_global, the
        # sample-sharded pod path, or the pod-sparse carrier-allgather
        # accumulator), whose every step was a collective — it already
        # holds the global sum and must not be "merged" again. Fail
        # loudly rather than guess: the pod driver paths never call
        # this function (pca gates on the mesh).
        raise ValueError(
            "allreduce_gramian merges HOST-LOCAL partial Gramians; this "
            "array is sharded across processes, which the global-mesh "
            "accumulators (packed dense AND pod-sparse) produce already "
            "globally summed — use their result directly instead of "
            "re-reducing it"
        )
    arr = jnp.asarray(g_local)
    n = arr.shape[0]
    itemsize = np.dtype(arr.dtype).itemsize
    rows = max(1, chunk_bytes // max(1, n * itemsize))
    out = np.empty(arr.shape, dtype=arr.dtype)
    with obs.span("allreduce_gramian", n=int(n), row_chunk=int(rows)):
        for r0 in range(0, n, rows):
            part = multihost_utils.process_allgather(arr[r0 : r0 + rows])
            out[r0 : r0 + rows] = np.asarray(
                jnp.sum(jnp.asarray(part), axis=0)
            )
    return jnp.asarray(out)


def allreduce_host_stats(stats: IoStats) -> IoStats:
    """Merge per-host IoStats across processes into global totals.

    Single-process: identity. Multi-process: all-gather the counter vector
    through the devices (the accumulator merge the Spark driver did).
    """
    if jax.process_count() == 1:
        return stats
    from jax.experimental import multihost_utils

    from spark_examples_tpu import obs

    vec = np.asarray(stats.as_vector(), dtype=np.int64)
    with obs.span("allreduce_host_stats"):
        total = np.asarray(
            multihost_utils.process_allgather(vec)
        ).sum(axis=0)
    # untracked: this is a merged VIEW of counters the registry
    # collector already sums from the per-source instances.
    merged = IoStats.untracked()
    merged.add(
        partitions=int(total[0]),
        reference_bases=int(total[1]),
        requests=int(total[2]),
        unsuccessful_responses=int(total[3]),
        io_exceptions=int(total[4]),
        variants_read=int(total[5]),
        reads_read=int(total[6]),
    )
    return merged
