"""Device-mesh construction — the topology half of ``--mesh-shape``.

The CLI spec grammar is ``"axis:size[,axis:size...]"`` (e.g. ``"data:4"``,
``"data:4,model:2"``), replacing the reference's ``--spark-master`` /
``--num-reduce-partitions`` knobs (GenomicsConf.scala:42-45,52-53): instead
of naming a cluster and a reducer count, name how devices factor over the
variant ("data") and sample ("model") axes.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

__all__ = ["make_mesh", "mesh_spans_processes", "DATA_AXIS", "MODEL_AXIS"]

DATA_AXIS = "data"
MODEL_AXIS = "model"


def mesh_spans_processes(mesh: Mesh) -> bool:
    """Does this mesh cross a process boundary (the pod regime)?

    The ONE topology predicate the accumulator routing keys on: a
    process-spanning mesh makes every accumulation step a collective
    (the per-step synced streams — ``_synced_block_stream`` for packed
    dense blocks, ``_synced_carrier_stream`` for sparse carrier
    windows), while a host-local mesh feeds devices independently.
    """
    return len({d.process_index for d in mesh.devices.flat}) > 1


def make_mesh(
    spec: Optional[str] = None, devices: Optional[Sequence] = None
) -> Mesh:
    """Build a Mesh from a spec string; default = all devices on "data"."""
    devices = list(devices) if devices is not None else jax.devices()
    if not spec:
        return Mesh(np.array(devices), (DATA_AXIS,))
    names, sizes = [], []
    for part in spec.split(","):
        try:
            name, size = part.strip().split(":")
            sizes.append(int(size))
        except ValueError:
            raise ValueError(
                f"bad mesh spec segment {part!r} in {spec!r}: expected "
                "'axis:size[,axis:size...]', e.g. 'data:4,model:2'"
            ) from None
        if sizes[-1] < 1:
            raise ValueError(
                f"mesh axis {name!r} has non-positive size {sizes[-1]} "
                f"in {spec!r}"
            )
        names.append(name)
    want = int(np.prod(sizes))
    if want > len(devices):
        raise ValueError(
            f"mesh spec {spec!r} needs {want} devices, have {len(devices)}"
        )
    arr = np.array(devices[:want]).reshape(sizes)
    return Mesh(arr, tuple(names))
