"""Sharded Gramian + PCoA under pjit/shard_map.

Two parallelism regimes, matching SURVEY.md §2.10's strategy table:

- **Variant-parallel (the DP/sequence-parallel analog).** V is huge, N
  moderate (the 1000-Genomes configs): each device holds a slice of the
  variant axis, computes a local partial ``X_loc @ X_loc.T``, and partial
  Gramians are ``psum``-reduced over the ring — the TPU-native replacement
  for the reference's per-task Breeze matrices + ``reduceByKey`` shuffle
  (VariantsPca.scala:184-191). Implemented with ``shard_map`` so the
  collective is explicit.

- **Sample-sharded (the TP analog).** N is huge (the synthetic 100k-sample
  stress config): G (N×N) lives 2D-sharded over (data, model); X rows are
  sharded and GSPMD inserts the all-gathers for ``X @ X.T``. The
  eigendecomposition at this scale cannot gather G to one device, so top-k
  eigenvectors come from :func:`topk_eig_randomized` — randomized subspace
  iteration whose only O(N²) op is ``C @ Q`` (shardable matmul); the
  (N, k+p) tall-skinny panel QR is done host-side-small per iteration.
"""

from __future__ import annotations

import functools
from functools import partial
from typing import Iterable

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from spark_examples_tpu.ops.centering import double_center
from spark_examples_tpu.ops.gramian import (
    mxu_cross_product,
    pack_indicator_block,
    resolve_gramian_compute_dtype,
    unpack_indicator_block,
)
from spark_examples_tpu.ops.pcoa import (
    DEFAULT_RANDOMIZED_OVERSAMPLE,
    SpectralGapWarning,
    check_spectral_gap,
    normalize_eigvec_signs,
    randomized_panel_width,
)
from spark_examples_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS

__all__ = [
    "SpectralGapWarning",
    "addressable_sample_bounds",
    "gramian_blockwise_global",
    "gramian_variant_parallel",
    "gramian_variant_parallel_ring",
    "sample_bounds_of_indices",
    "sharded_gramian_blockwise",
    "sharded_gramian_blockwise_global",
    "sharded_pcoa",
    "sharded_sketch_finish",
    "sharded_sketch_panel",
    "sketch_tsqr",
    "sparse_sharded_gramian_blockwise",
    "topk_eig_randomized",
]


def _mesh_axes(mesh: Mesh):
    has_model = MODEL_AXIS in mesh.axis_names
    return DATA_AXIS, (MODEL_AXIS if has_model else None)


def _shard_map(f, mesh: Mesh, in_specs, out_specs, check_vma=None):
    """``jax.shard_map`` across the jax versions this tree runs on.

    Newer jax exposes it at top level (with ``check_vma``); 0.4.x keeps
    it in ``jax.experimental.shard_map`` where the same knob is spelled
    ``check_rep``. One seam so every per-device kernel here stays
    runnable on both.
    """
    if hasattr(jax, "shard_map"):
        kw = {} if check_vma is None else {"check_vma": check_vma}
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw
        )
    from jax.experimental.shard_map import shard_map as _sm

    kw = {} if check_vma is None else {"check_rep": check_vma}
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw)


def gramian_variant_parallel(x, mesh: Mesh, compute_dtype=None):
    """``G = psum_over_devices(X_loc @ X_loc.T)`` with X variant-sharded.

    ``x``: (N, V) with V divisible by the data-axis size. Returns G
    replicated (N small enough to replicate in this regime).
    """
    compute_dtype = resolve_gramian_compute_dtype(
        x.dtype, jnp.float32, compute_dtype
    )

    @partial(
        _shard_map,
        mesh=mesh,
        in_specs=P(None, DATA_AXIS),
        out_specs=P(None, None),
    )
    def _local_gramian(x_loc):
        g_loc = mxu_cross_product(x_loc, jnp.float32, compute_dtype)
        return jax.lax.psum(g_loc, DATA_AXIS)

    return jax.jit(_local_gramian)(x)


def _axis_product(mesh: Mesh, spec: P) -> int:
    total = 1
    for entry in spec:
        if entry is None:
            continue
        for name in entry if isinstance(entry, tuple) else (entry,):
            total *= mesh.shape[name]
    return total


def _mesh_spans_processes(mesh: Mesh) -> bool:
    from spark_examples_tpu.parallel.mesh import mesh_spans_processes

    return mesh_spans_processes(mesh)


# dtype.num ↔ dtype for the cross-process dtype agreement (allgather moves
# int64 codes, not dtype objects); covers every block dtype a producer can
# legitimately emit (indicators, dosages, counts).
_DTYPE_BY_NUM = {
    np.dtype(t).num: np.dtype(t)
    for t in (
        np.bool_,
        np.int8,
        np.uint8,
        np.int16,
        np.int32,
        np.int64,
        np.float16,
        np.float32,
        np.float64,
    )
}


def _dtype_name(num: int):
    dt = _DTYPE_BY_NUM.get(num)
    return str(dt) if dt is not None else f"dtype.num={num}"


def _accumulate_blocks(
    blocks,
    n_samples: int,
    mesh: Mesh,
    x_sharding: NamedSharding,
    g_sharding: NamedSharding,
    compute_dtype,
    accum_dtype,
    packed: bool = False,
    prefetch_depth: int = 2,
):
    """Shared blockwise-Gramian core: pad, zero-init, accumulate, trim.

    The layout policy lives entirely in the two shardings; the feed policy
    follows the mesh — a process-spanning mesh gets the width/liveness-
    synced global stream, a host-local mesh a plain device prefetch (each
    host accumulating its own partial independently).

    The sample axis is padded to a multiple of the G-sharding axis sizes:
    N comes from the cohort's callset count, which is arbitrary, and
    device_put needs sharded dimensions to divide evenly. Zero rows are
    inert in X @ X.T (zero rows/cols of G), trimmed before returning.

    ``packed=True`` (for 0/1 indicator blocks only) bit-packs each block
    host-side after padding — 8× fewer bytes over every host→device feed,
    the same on-chip-measured win as the single-device path — and the
    jitted accumulator unpacks before the matmul. The packed column count
    is zero-byte-padded up to the variant-axis sharding divisor (zero
    bytes unpack to inert zero columns), and the synced global stream
    syncs on packed widths, which preserves its no-one-sided-deadlock
    guarantee (equal packed widths ⇒ equal global shapes).
    """
    from spark_examples_tpu.arrays.blocks import round_up_multiple

    n_padded = round_up_multiple(
        n_samples, _axis_product(mesh, g_sharding.spec)
    )
    v_spec = (
        x_sharding.spec[1] if len(x_sharding.spec) > 1 else None
    )
    v_div = _axis_product(mesh, P(v_spec))

    # Resolve the MXU dtype policy (incl. the SPARK_EXAMPLES_TPU_GRAMIAN
    # env escape hatch) OUTSIDE the trace, per accumulation stream —
    # mxu_cross_product's contract. The packed path always unpacks to
    # int8; the unpacked path resolves from the first block's REAL dtype
    # (a float dosage block must compute in float, not truncate to int8),
    # peeked here and pushed back onto the stream. On a process-spanning
    # mesh the peeked dtype is AGREED cross-process (same protocol shape
    # as the width sync): a process whose stream is empty would otherwise
    # default to int8 while float peers compile a different executable
    # around the same collectives — divergent programs, hang or garbage.
    # A real dtype mismatch raises on every process simultaneously.
    if packed:
        x_dtype = np.dtype(np.int8)
    else:
        blocks = iter(blocks)
        # The peek itself can raise (the producer runs ingest): on a
        # process-spanning mesh that raise must ride the agreement
        # collective below (code −2) like every later step's does in
        # _synced_block_stream, or one host dies pre-collective while
        # peers block in the allgather forever.
        peek_exc = None
        try:
            first = next(blocks, None)
        except Exception as e:  # noqa: BLE001 — re-raised below, synced
            peek_exc, first = e, None
        x_dtype = (
            np.dtype(np.int8) if first is None else np.asarray(first).dtype
        )
        if _mesh_spans_processes(mesh):
            from jax.experimental import multihost_utils

            # Raw num goes into the collective UNVALIDATED — validation
            # happens after the gather, on identical data everywhere, so
            # an unsupported dtype raises on every process together
            # instead of one process erroring pre-collective while peers
            # block in the allgather.
            if peek_exc is not None:
                local_num = -2
            else:
                local_num = -1 if first is None else x_dtype.num
            nums = np.asarray(
                multihost_utils.process_allgather(
                    np.array([local_num], np.int64)
                )
            ).ravel()
            failed = [i for i, v in enumerate(nums) if int(v) == -2]
            if failed:
                raise RuntimeError(
                    "block stream failed on process(es) "
                    f"{failed} while peeking the first block; raising "
                    "on every process together (a one-sided raise "
                    "would strand peers in the collective)"
                ) from peek_exc
            present = sorted({int(v) for v in nums if v >= 0})
            unsupported = [n for n in present if n not in _DTYPE_BY_NUM]
            if unsupported:
                raise ValueError(
                    "unsupported block dtype(s) in the pod-mode stream: "
                    f"{[_dtype_name(n) for n in unsupported]}; supported: "
                    f"{sorted(str(d) for d in _DTYPE_BY_NUM.values())}"
                )
            if len(present) > 1:
                raise ValueError(
                    "block dtypes differ across processes: "
                    f"{[_dtype_name(n) for n in present]}; "
                    "every host must stream the same block dtype"
                )
            if present:
                x_dtype = _DTYPE_BY_NUM[present[0]]
        if peek_exc is not None:
            # Single-process mesh: no peer to strand; surface directly.
            raise peek_exc
        if first is not None:
            import itertools

            blocks = itertools.chain((first,), blocks)
    compute_dtype = resolve_gramian_compute_dtype(
        x_dtype, accum_dtype, compute_dtype
    )

    @partial(jax.jit, donate_argnums=(0,), out_shardings=g_sharding)
    def _accum(g, xb):
        if packed:
            xb = unpack_indicator_block(xb, 8 * xb.shape[1])
        return g + mxu_cross_product(xb, g.dtype, compute_dtype)

    spans = _mesh_spans_processes(mesh)

    def padded_blocks():
        for block in blocks:
            xb = np.asarray(block)
            # Mid-stream dtype drift would retrace _accum with the WRONG
            # (stream-agreed) compute_dtype — e.g. float dosages truncated
            # through an int8 executable. Catch it locally here on
            # single-process meshes; the pod path defers to the per-step
            # synced check so the raise is never one-sided.
            if not packed and not spans and xb.dtype != x_dtype:
                raise ValueError(
                    f"block dtype changed mid-stream: {xb.dtype} after the "
                    f"stream was resolved as {x_dtype}; every block must "
                    "share one dtype"
                )
            if n_padded != n_samples:
                xb = np.pad(xb, ((0, n_padded - n_samples), (0, 0)))
            if packed:
                xb = pack_indicator_block(xb)
                cols = round_up_multiple(xb.shape[1], v_div)
                if cols != xb.shape[1]:
                    xb = np.pad(xb, ((0, 0), (0, cols - xb.shape[1])))
            yield xb

    g = jax.device_put(
        jnp.zeros((n_padded, n_padded), dtype=accum_dtype), g_sharding
    )
    # Zero-fill for drained streams must match the agreed block dtype, or
    # a drained float peer would feed int8 shards into the same global
    # array its neighbours build from float32.
    fill_dtype = np.dtype(np.uint8) if packed else x_dtype
    if spans:
        stream = _synced_block_stream(
            padded_blocks(), n_padded, x_sharding, fill_dtype=fill_dtype
        )
    else:
        from spark_examples_tpu.arrays.feed import device_prefetch

        stream = device_prefetch(
            padded_blocks(), depth=prefetch_depth, sharding=x_sharding
        )
    for xb in stream:
        g = _accum(g, xb)
    if n_padded == n_samples:
        return g
    # Trim as a (collective, when process-spanning) jit slice so the
    # result is never gathered to a host. No explicit out-sharding: the
    # trimmed dims need not divide the mesh axes; GSPMD keeps the layout
    # as close as the uneven shape allows.
    return jax.jit(lambda a: a[:n_samples, :n_samples])(g)


def sharded_gramian_blockwise(
    blocks: Iterable[np.ndarray],
    n_samples: int,
    mesh: Mesh,
    accum_dtype=jnp.float32,
    compute_dtype=None,
    packed: bool = False,
    prefetch_depth: int = 2,
):
    """Stream variant blocks into a mesh-sharded Gramian accumulator.

    G is laid out P(data, model) — 2D-sharded when the mesh has a model
    axis, row-sharded otherwise; X blocks arrive row-sharded P(data, None).
    GSPMD inserts the all-gather of X over the partial axis; accumulation
    stays in place in HBM (donated).
    """
    d_axis, m_axis = _mesh_axes(mesh)
    return _accumulate_blocks(
        blocks,
        n_samples,
        mesh,
        NamedSharding(mesh, P(d_axis, None)),
        NamedSharding(mesh, P(d_axis, m_axis)),
        compute_dtype,
        accum_dtype,
        packed=packed,
        prefetch_depth=prefetch_depth,
    )


def gramian_variant_parallel_ring(x, mesh: Mesh, compute_dtype=None):
    """Variant-parallel Gramian with an explicit ring reduction.

    Same math as :func:`gramian_variant_parallel` but the cross-device
    reduction is hand-scheduled as a ``ppermute`` ring instead of a single
    ``psum``: each step sends the running buffer to the next ICI neighbor
    and accumulates, so per-link traffic is balanced and each hop can
    overlap with other work — the ring-attention communication shape
    applied to the genomics "sequence" axis (the variant axis). XLA's
    psum typically lowers to an equivalent schedule on a ring ICI; this
    form makes the schedule explicit (and testable) as SURVEY.md §2.10's
    ring/blockwise analog.
    """
    compute_dtype = resolve_gramian_compute_dtype(
        x.dtype, jnp.float32, compute_dtype
    )
    n_dev = mesh.shape[DATA_AXIS]
    perm = [(j, (j + 1) % n_dev) for j in range(n_dev)]

    @partial(
        _shard_map,
        mesh=mesh,
        in_specs=P(None, DATA_AXIS),
        out_specs=P(None, None),
        # After n_dev−1 ring hops every device holds the full sum, but the
        # static replication checker cannot prove it through ppermute.
        check_vma=False,
    )
    def _ring(x_loc):
        g_loc = mxu_cross_product(x_loc, jnp.float32, compute_dtype)

        def body(_, carry):
            acc, buf = carry
            buf = jax.lax.ppermute(buf, DATA_AXIS, perm)
            return acc + buf, buf

        acc, _ = jax.lax.fori_loop(0, n_dev - 1, body, (g_loc, g_loc))
        # Each device accumulated the same partials in a rotated order;
        # float non-associativity would make the "replicated" shards
        # bitwise-divergent (exact for 0/1 inputs, not for dosage-valued
        # X). Canonicalize by broadcasting device 0's copy so every shard
        # is identical regardless of input values.
        idx = jax.lax.axis_index(DATA_AXIS)
        return jax.lax.psum(
            jnp.where(idx == 0, acc, jnp.zeros_like(acc)), DATA_AXIS
        )

    return jax.jit(_ring)(x)


def gramian_blockwise_global(
    local_blocks,
    n_samples: int,
    mesh: Mesh,
    compute_dtype=None,
    accum_dtype=jnp.float32,
    packed: bool = False,
    prefetch_depth: int = 2,
):
    """Multi-controller blockwise Gramian: one mesh spanning every process.

    The TPU-pod execution model (multi-host GSPMD): each process ingests
    its own variant columns (its slice of the shard manifest) and
    contributes them as the process-local shard of a *global* block via
    ``jax.make_array_from_process_local_data``; the variant axis is sharded
    over all mesh axes, G stays replicated, and XLA emits the cross-chip
    reduction over ICI/DCN — no host-side gather of G at all (unlike
    :func:`spark_examples_tpu.parallel.distributed.allreduce_gramian`,
    which merges host-local partials through host memory).

    Hosts may ingest different numbers of blocks; every block step is a
    collective, so liveness and block width are synchronized per block
    with a tiny host allgather — a process whose stream is exhausted
    feeds zero columns (inert in the Gramian) at the peers' width until
    all streams drain, and a width mismatch raises on every process
    simultaneously (never a one-sided deadlock).
    """
    return _accumulate_blocks(
        local_blocks,
        n_samples,
        mesh,
        NamedSharding(mesh, P(None, tuple(mesh.axis_names))),
        NamedSharding(mesh, P(None, None)),
        compute_dtype,
        accum_dtype,
        packed=packed,
        prefetch_depth=prefetch_depth,
    )


def _synced_block_stream(
    local_blocks, n_samples: int, x_sharding, fill_dtype=np.int8
):
    """Per-step width/liveness-synced global blocks from per-process streams.

    Factored from the pod-mode accumulators: every process learns every
    peer's block width (−1 = exhausted) BEFORE any collective compute, so
    width mismatches raise on ALL processes together (one process raising
    alone would leave peers deadlocked in the next collective) and an
    exhausted process zero-fills at the peers' width until all streams
    drain (zero columns are inert in the Gramian).

    The same message carries each block's dtype.num: the upfront
    agreement in ``_accumulate_blocks`` only sees FIRST blocks, so a
    mid-stream dtype divergence (or a coordinated mid-stream switch away
    from the dtype the executable was compiled for) must be caught per
    step — again on every process simultaneously, from identical
    gathered data.

    Producer exceptions ride the same message (width code −2): the
    upstream generator runs host-side validation (e.g.
    ``pack_indicator_block``'s 0/1-indicator check) whose raise would
    otherwise fire on ONE process before its allgather post, leaving
    peers blocked in the collective forever. Instead the failing process
    posts −2 and every process raises together, the failing one chaining
    its original exception.
    """
    from jax.experimental import multihost_utils

    expected_num = fill_dtype.num
    it = iter(local_blocks)
    while True:
        exc = None
        try:
            block = next(it, None)
        except Exception as e:  # noqa: BLE001 — synced below, see docstring
            exc, block = e, None
        if exc is not None:
            w, num, rows = -2, -1, -1
        elif block is None:
            w, num, rows = -1, -1, -1
        else:
            block = np.asarray(block)
            w, num, rows = (
                int(block.shape[1]),
                block.dtype.num,
                int(block.shape[0]),
            )
        peer_info = np.asarray(
            multihost_utils.process_allgather(
                np.array([w, num, rows], np.int64)
            )
        ).reshape(-1, 3)
        failed = [
            i for i, (x, _, _) in enumerate(peer_info) if int(x) == -2
        ]
        if failed:
            # exc is None on healthy peers — `from None` is a no-op there.
            raise RuntimeError(
                "block stream failed on process(es) "
                f"{failed}; raising on every process together (a "
                "one-sided raise would strand peers in the next "
                "collective)"
            ) from exc
        # Row counts ride the same message: widths/dtypes can agree while
        # one process's block has the wrong sample count — that would pass
        # this sync and then diverge one-sided inside the collective
        # accumulate (rows are the UNsharded dim, inferred from local
        # data). n_samples here is the caller's padded N.
        bad_rows = sorted(
            {int(r) for x, _, r in peer_info if x >= 0 and r != n_samples}
        )
        if bad_rows:
            raise ValueError(
                f"block row counts {bad_rows} differ from the padded "
                f"sample count {n_samples}; every host must stream "
                "blocks over the full (padded) sample axis"
            )
        live = sorted({int(x) for x, _, _ in peer_info if x >= 0})
        if not live:
            return
        bad_nums = sorted(
            {int(n) for x, n, _ in peer_info if x >= 0 and n != expected_num}
        )
        if bad_nums:
            raise ValueError(
                "block dtype diverged mid-stream: got "
                f"{[_dtype_name(n) for n in bad_nums]} where the stream "
                f"was resolved as {_dtype_name(expected_num)}; every host "
                "must stream one dtype for the whole accumulation"
            )
        if len(live) > 1:
            raise ValueError(
                "block widths differ across processes in the same step: "
                f"{live}; every host must stream fixed-width blocks "
                "(blocks_from_calls pads) with the same --block-variants"
            )
        width = live[0]
        if block is None:
            block = np.zeros((n_samples, width), fill_dtype)
        yield jax.make_array_from_process_local_data(
            x_sharding, np.asarray(block)
        )


def sharded_gramian_blockwise_global(
    local_blocks,
    n_samples: int,
    mesh: Mesh,
    compute_dtype=None,
    accum_dtype=jnp.float32,
    packed: bool = False,
    prefetch_depth: int = 2,
):
    """Pod-mode blockwise Gramian with G *sample-sharded* over the mesh.

    The 100k-sample stress regime (BASELINE.md config #5): N is too large
    to replicate G per device (100k² f32 = 40 GB), so G lives 2D-sharded
    ``P(data, model)`` across the whole multi-process mesh while each
    process feeds its own variant columns — the combination the reference
    could not reach at all (its per-task dense matrix capped it near 50k
    samples in 20 GB heaps, VariantsPca.scala:176-177). Per-step sync and
    zero-fill semantics are identical to :func:`gramian_blockwise_global`;
    the only difference is the output layout, which GSPMD propagates into
    the einsum (each device builds its own G tile from the gathered block
    columns — the block all-gather rides ICI, G never moves).

    Returns G still sharded; downstream :func:`sharded_pcoa` consumes it
    without ever gathering at large N.
    """
    d_axis, m_axis = _mesh_axes(mesh)
    return _accumulate_blocks(
        local_blocks,
        n_samples,
        mesh,
        NamedSharding(mesh, P(None, tuple(mesh.axis_names))),
        NamedSharding(mesh, P(d_axis, m_axis)),
        compute_dtype,
        accum_dtype,
        packed=packed,
        prefetch_depth=prefetch_depth,
    )


def sample_bounds_of_indices(index_slices, n: int):
    """``(lo, hi)`` union of the sample ranges a tile set touches.

    ``index_slices`` are the per-device ``(row_slice, col_slice)`` pairs
    of an ``addressable_devices_indices_map`` over the (n, n) Gramian: a
    host whose tiles cover rows R and columns C only ever reads carrier
    indices inside ``R ∪ C`` — every pair with either endpoint outside
    the union lands in a tile some OTHER host owns. This is the per-host
    sample-range ingest contract (docs/ARCHITECTURE.md): ingest may
    drop carriers outside the bounds before they ever reach the device
    feed, bit-identically (pinned by test).
    """
    lo, hi = n, 0
    for row_sl, col_sl in index_slices:
        for sl in (row_sl, col_sl):
            start = sl.start if sl.start is not None else 0
            stop = sl.stop if sl.stop is not None else n
            lo, hi = min(lo, start), max(hi, stop)
    if hi <= lo:
        return 0, n
    return lo, hi


def addressable_sample_bounds(mesh: Mesh, g_sharding, n: int):
    """This process's sample-range bounds for a sharded (n, n) Gramian."""
    index_map = g_sharding.addressable_devices_indices_map((n, n))
    return sample_bounds_of_indices(index_map.values(), n)


@functools.lru_cache(maxsize=64)
def _sparse_tile_kernels(
    mesh: Mesh,
    d_axis,
    m_axis,
    n_padded: int,
    tile_rows: int,
    tile_cols: int,
    accum_name: str,
    compute_name: str,
    scatter_path: str = "scan",
    mirror: bool = False,
):
    """Compiled kernel set (tile scatter, GSPMD dense fallback, pod
    dense tile step, symmetric-mirror finalizer) for one (mesh,
    padded-N, dtype, scatter-path, mirror) geometry — cached on the
    hashable geometry key. ``jax.jit`` caches by function identity, so
    building these as fresh closures per accumulation call would
    re-trace and re-compile the shard_map program on EVERY call (the
    bench sweep's repeats and per-job driver runs would measure XLA
    compilation, not accumulation); the lru_cache pins one executable
    per geometry.

    ``scatter_path`` is the pre-resolved scan-vs-Pallas choice
    (:func:`spark_examples_tpu.ops.scatter_kernel.resolve_scatter_path`,
    resolved OUTSIDE the trace by the accumulator entry point) — part of
    the cache key so the env kill switch takes effect per stream.

    ``mirror=True`` (square tile grids on the pod path) exploits G's
    symmetry: an off-diagonal tile is exactly its transpose partner's
    transpose, so each partner computes only HALF — the upper device
    its tile's top row-slab, the lower device its right column-slab
    (complementary under transposition, so the pair's work splits
    evenly across the two owning processes instead of idling one) —
    and one final ``ppermute`` swap + transpose reassembles both
    tiles, bit-exactly (pure copies of exact integer counts). On a g×g
    grid this removes the g(g−1)/2 redundant off-diagonal tile
    computations the pair-space tiling otherwise duplicates across the
    diagonal: the dense route halves its off-diagonal MXU work
    (scatter updates are index-driven, so there the masking only keeps
    the partition consistent). The all_gather stays unconditional on
    every device — no collective ever sits inside a skipped branch.
    """
    from spark_examples_tpu.ops.gramian import mxu_cross_product_pair
    from spark_examples_tpu.ops.scatter_kernel import scatter_pairs_kernel
    from spark_examples_tpu.ops.sparse import scatter_pairs_chunked

    compute_dtype = jnp.dtype(compute_name)
    g_sharding = NamedSharding(mesh, P(d_axis, m_axis))

    def _grid_pos():
        d_idx = jax.lax.axis_index(d_axis)
        m_idx = (
            jax.lax.axis_index(m_axis)
            if m_axis is not None
            else jnp.int32(0)
        )
        return d_idx, m_idx

    def _scatter_impl(g_tile, li, lj):
        if scatter_path == "scan":
            return scatter_pairs_chunked(g_tile, li, lj)
        return scatter_pairs_kernel(
            g_tile, li, lj, interpret=scatter_path == "interpret"
        )

    half = tile_rows // 2  # mirror slab split (tiles square there)

    def _tile_scatter(g_tile, idx):
        # Re-base global carrier indices into this device's tile frame;
        # anything outside the tile becomes an out-of-bounds sentinel
        # and the drop-mode scatter ignores it. Tiles partition the
        # (i, j) pair space, so the union over devices is exactly one
        # +1 per co-occurring pair — the dense path's count.
        d_idx, m_idx = _grid_pos()
        r0 = d_idx * tile_rows
        c0 = m_idx * tile_cols
        li = jnp.where(
            (idx >= r0) & (idx < r0 + tile_rows), idx - r0, tile_rows
        )
        lj = jnp.where(
            (idx >= c0) & (idx < c0 + tile_cols), idx - c0, tile_cols
        )
        if mirror:
            # Off-diagonal slab partition: the upper partner owns its
            # top row-slab, the lower its right column-slab; the rest
            # is OOB here and reconstructed by the final mirror.
            li = jnp.where(
                jnp.logical_and(d_idx < m_idx, li >= half),
                tile_rows,
                li,
            )
            lj = jnp.where(
                jnp.logical_and(d_idx > m_idx, lj < half),
                tile_cols,
                lj,
            )
        return _scatter_impl(g_tile, li, lj)

    scatter = jax.jit(
        _shard_map(
            _tile_scatter,
            mesh=mesh,
            in_specs=(P(d_axis, m_axis), P(None, None)),
            out_specs=P(d_axis, m_axis),
        ),
        donate_argnums=(0,),
    )

    @partial(jax.jit, donate_argnums=(0,), out_shardings=g_sharding)
    def _accum_dense(g, xp):
        xb = unpack_indicator_block(xp, 8 * xp.shape[1])
        return g + mxu_cross_product(xb, g.dtype, compute_dtype)

    all_axes = tuple(mesh.axis_names)

    def _tile_dense_pod(g_tile, xp_loc):
        # The pod dense step as ONE explicit shard_map program: gather
        # the bit-PACKED panel bytes over every mesh axis (8× fewer
        # bytes over DCN than the unpacked X the GSPMD formulation
        # moved), unpack locally on each device, slice this tile's row
        # and column sample ranges, and accumulate the cross matmul.
        # The GSPMD version of this step forced an involuntary full
        # rematerialization of the (N, V, 8) unpack broadcast on the
        # process-spanning mesh (XLA spmd_partitioner warning) — ~14×
        # the runtime of this explicit form at the MULTICHIP bench
        # shape, measured in PERFORMANCE.md's decision log.
        # The all_gather runs UNCONDITIONALLY on every device (a
        # collective inside a skipped branch would strand peers); only
        # the local unpack + matmul shrinks under mirror.
        xp = jax.lax.all_gather(xp_loc, all_axes, axis=1, tiled=True)
        d_idx, m_idx = _grid_pos()
        r0 = d_idx * tile_rows
        c0 = m_idx * tile_cols

        def _mm(row_start, n_rows, col_start, n_cols):
            # Slice the PACKED panel's sample rows first (packing is
            # along the variant axis, so row slicing is exact), then
            # unpack only the two slabs — never the full (N, V) panel
            # per device (that full-unpack transient is the same waste
            # this program exists to remove from the GSPMD form).
            rows = unpack_indicator_block(
                jax.lax.dynamic_slice(
                    xp, (row_start, 0), (n_rows, xp.shape[1])
                ),
                8 * xp.shape[1],
            )
            cols = unpack_indicator_block(
                jax.lax.dynamic_slice(
                    xp, (col_start, 0), (n_cols, xp.shape[1])
                ),
                8 * xp.shape[1],
            )
            return mxu_cross_product_pair(
                rows, cols, g_tile.dtype, compute_dtype
            )

        if not mirror:
            return g_tile + _mm(r0, tile_rows, c0, tile_cols)
        # Slab partition (see docstring): upper partner computes only
        # its top row-slab, lower only its right column-slab — half the
        # MXU work each; diagonal tiles compute in full. lax.cond
        # executes one branch, so the skipped halves cost nothing.
        return jax.lax.cond(
            d_idx == m_idx,
            lambda g: g + _mm(r0, tile_rows, c0, tile_cols),
            lambda g: jax.lax.cond(
                d_idx < m_idx,
                lambda gg: gg.at[:half, :].add(
                    _mm(r0, half, c0, tile_cols)
                ),
                lambda gg: gg.at[:, half:].add(
                    _mm(r0, tile_rows, c0 + half, tile_cols - half)
                ),
                g,
            ),
            g_tile,
        )

    accum_dense_pod = jax.jit(
        _shard_map(
            _tile_dense_pod,
            mesh=mesh,
            in_specs=(P(d_axis, m_axis), P(None, all_axes)),
            out_specs=P(d_axis, m_axis),
        ),
        donate_argnums=(0,),
    )

    mirror_fill = None
    if mirror:
        grid_rows = mesh.shape[d_axis]
        grid_cols = mesh.shape[m_axis] if m_axis is not None else 1
        # Full involution over the tile grid: device (i, j) receives
        # tile (j, i) and reassembles its own tile from the slab
        # partition — the upper partner computed rows [0, half), so
        # transpose(partner) provides the lower partner's columns
        # [0, half), and vice versa; diagonal tiles are already whole.
        perm = [
            (i * grid_cols + j, j * grid_cols + i)
            for i in range(grid_rows)
            for j in range(grid_cols)
        ]

        def _mirror_tiles(g_tile):
            d_idx, m_idx = _grid_pos()
            swapped = jax.lax.ppermute(
                g_tile, (d_axis, m_axis), perm
            )
            st = jnp.swapaxes(swapped, 0, 1)
            # Upper tile: own rows [0, half) + partner's column slab
            # transposed (= rows [half, tile)). Lower tile: partner's
            # row slab transposed (= columns [0, half)) + own columns
            # [half, tile). Exact copies of exact integer counts.
            upper = jnp.concatenate(
                [g_tile[:half, :], st[half:, :]], axis=0
            )
            lower = jnp.concatenate(
                [st[:, :half], g_tile[:, half:]], axis=1
            )
            return jnp.where(
                d_idx == m_idx,
                g_tile,
                jnp.where(d_idx < m_idx, upper, lower),
            )

        mirror_fill = jax.jit(
            _shard_map(
                _mirror_tiles,
                mesh=mesh,
                in_specs=P(d_axis, m_axis),
                out_specs=P(d_axis, m_axis),
            ),
            donate_argnums=(0,),
        )

    return scatter, _accum_dense, accum_dense_pod, mirror_fill


@partial(jax.jit, static_argnames=("n",))
def _trim_square(a, n: int):
    return a[:n, :n]


# Route codes on the pod-sparse header wire (field 0 doubles as the
# liveness code): −2 producer exception, −1 stream exhausted, else the
# window's density-route decision.
_ROUTE_CODES = {"scatter": 0, "dense": 1}
_ROUTE_OF_CODE = {v: k for k, v in _ROUTE_CODES.items()}


def _synced_carrier_stream(
    windows,
    n_samples: int,
    n_padded: int,
    mesh: Mesh,
    density_threshold: float,
    dense_width: int,
    v_div: int,
    x_sharding,
    idx_sharding,
    pipeline_depth: int = 2,
    coalesce_variants: int = None,
):
    """Pipelined per-step header/carrier exchange of global windows from
    per-process CSR streams — the sparse twin of
    :func:`_synced_block_stream` (ROADMAP item 2's pod half), rebuilt as
    a depth-D pipeline over the host-side coordination-service exchange
    (:mod:`spark_examples_tpu.parallel.podstream`, ROADMAP item 3).

    Every sparse accumulation step on a process-spanning mesh runs one
    collective device program (the tile scatter / the pod dense tile
    matmul over the whole mesh), so per step every process FIRST agrees
    a tiny host header — ``[route/liveness code, k_max, variant rows,
    payload dtype.num, nnz, windows]`` — and only then enters the
    payload phase. The agreement used to ride device allgathers
    enqueued behind the previous window's scatter on each device's
    serial stream — collective latency serialized against compute. Now
    header, payload-confirm, and carrier exchange are pure host RPCs on
    a sync thread: window ``w+1``'s whole protocol step (including its
    densify/pack/carrier-padding host work) runs while window ``w``'s
    scatter executes on device, ``pipeline_depth`` slots ahead
    (``0`` = inline lockstep, the ablation mode). The failure-sync
    discipline carries over slot-by-slot:

    - a process whose stream is exhausted posts −1 and keeps feeding
      inert payloads (all-sentinel carrier rows, or zero packed columns
      on dense steps) until every stream drains — zero contributions
      are inert in the Gramian, so stragglers never strand peers;
    - a producer exception posts −2 and every process raises together
      at the SAME slot position, the failing one chaining its original
      exception (a one-sided raise would leave peers blocked in the
      next device collective forever). The per-shard retry seams run
      INSIDE the producer, upstream of this sync; post-header LOCAL
      payload construction failures are covered by the payload-confirm
      exchange before any payload moves, for every in-flight slot;
    - the density route is a per-step GLOBAL decision (both routes are
      collective device programs — half the pod cannot scatter while
      the other half matmuls): the header carries each process's local
      :func:`spark_examples_tpu.ops.sparse.window_route` decision and a
      divergent step raises on every process together (pin
      ``--sparse-density-threshold`` to 0 or large to force one route
      on heterogeneous cohorts);
    - carrier widths are NOT required to agree — ragged windows are the
      norm — instead every process pads to the power-of-two bucket of
      the GLOBAL max width (and to the global max variant-row count),
      so the collective scatter executable caches per geometry across
      hosts;
    - tiny scatter-route windows COALESCE into one gang per step
      (consecutive local windows until their variant-row total reaches
      ``coalesce_variants``; a dense-route window ends the gang and
      becomes its own step), so per-step exchange latency amortizes
      over many windows — bit-identical at any gang split (exact
      integer accumulation, pinned by tests).

    Scatter steps exchange the padded ``(rows, k_bucket)`` int32
    carrier matrices host-side (~d·N·V_blk integers — tiny next to the
    dense packed panels; a drained peer's inert all-sentinel block is
    synthesized locally from its header, zero bytes moved) and every
    device re-bases the concatenated global matrix into its tile frame
    for the same OOB-drop scatter; dense steps carry this process's
    packed panel columns into the pod dense tile program
    (packed-bytes all_gather inside the shard_map — see
    ``_sparse_tile_kernels``).

    Yields ``(route, global_payload, local_nnz, local_variants, step,
    local_windows, stream_id)``. Device arrays are built HERE, on the consumer
    thread — the sync thread never touches jax, so the device
    collective launch order stays identical on every process.
    """
    from spark_examples_tpu import obs
    from spark_examples_tpu.arrays.blocks import (
        _check_indices,
        _densify_window,
        round_up_multiple,
    )
    from spark_examples_tpu.ops.gramian import pack_indicator_block
    from spark_examples_tpu.ops.sparse import (
        DEFAULT_POD_COALESCE_VARIANTS,
        _carrier_bucket,
        _note_pod_gang,
        _note_pod_sync,
        _pad_rows_for_scan,
        dense_panel_width,
        padded_carrier_matrix,
        window_route,
    )
    from spark_examples_tpu.parallel.podstream import (
        PodSlot,
        PodWindowExchange,
        SlotPipeline,
    )
    from spark_examples_tpu.utils import collectivecheck

    if coalesce_variants is None:
        coalesce_variants = DEFAULT_POD_COALESCE_VARIANTS
    if pipeline_depth < 0:
        raise ValueError(
            f"--pod-pipeline-depth must be >= 0, got {pipeline_depth}"
        )
    # Resolved HERE, on the consumer thread: the sync thread must
    # never touch jax (the segfault-safety basis of the host-side
    # exchange design — see podstream's module docstring).
    world = jax.process_count()
    pid = jax.process_index()
    exchange = PodWindowExchange.open()
    if exchange is None:
        raise RuntimeError(
            "process-spanning sparse accumulation needs the "
            "jax.distributed coordination service for its host-side "
            "window exchange; initialize via parallel.distributed."
            "initialize_from_env (any multi-process jax run has it)"
        )

    it = iter(windows)
    pushback: list = []

    def _pull():
        if pushback:
            return pushback.pop()
        return next(it, None)

    def _gang():
        """This step's local windows: ``[]`` when drained, ONE
        dense-route window, or 1+ scatter-route windows coalesced until
        the variant-row total reaches ``coalesce_variants``."""
        first = _pull()
        if first is None:
            return [], None
        idx = np.asarray(first[0], dtype=np.int64)
        lens = np.asarray(first[1], dtype=np.int64)
        _check_indices(idx, n_samples)
        route = window_route(lens, n_samples, density_threshold)
        gang = [(idx, lens)]
        if route == "dense":
            return gang, route
        total = int(lens.size)
        while total < coalesce_variants:
            nxt = _pull()
            if nxt is None:
                break
            nidx = np.asarray(nxt[0], dtype=np.int64)
            nlens = np.asarray(nxt[1], dtype=np.int64)
            _check_indices(nidx, n_samples)
            if (
                window_route(nlens, n_samples, density_threshold)
                != "scatter"
            ):
                # A dense window ends the gang and becomes the NEXT
                # step — the route stays a per-step global decision.
                pushback.append((nidx, nlens))
                break
            gang.append((nidx, nlens))
            total += int(nlens.size)
        return gang, "scatter"

    state = {"step": 0}

    def _produce_step(step):
        exc = None
        gang: list = []
        code, k_max, rows, num, nnz, nwin = -1, -1, -1, -1, 0, 0
        try:
            gang, route_local = _gang()
            if gang:
                all_lens = [lens for _, lens in gang]
                code = _ROUTE_CODES[route_local]
                k_max = max(
                    (int(lens.max()) if lens.size else 0)
                    for lens in all_lens
                )
                rows = sum(int(lens.size) for lens in all_lens)
                nnz = sum(int(lens.sum()) for lens in all_lens)
                nwin = len(gang)
                # The PAYLOAD dtype rides the wire: int32 carrier
                # matrices on scatter steps, packed uint8 panels on
                # dense ones — agreed from identical gathered data so a
                # divergence raises everywhere.
                num = np.dtype(
                    np.int32 if route_local == "scatter" else np.uint8
                ).num
        except Exception as e:  # noqa: BLE001 — synced below, see docstring
            exc, code = e, -2
        # The collective-check backstop's enablement rides the header
        # (field 6) so the digest exchange below is an AGREED step: it
        # runs only when every process advertised it, and a
        # mixed-enablement pod degrades to unchecked instead of
        # desyncing on unexpected frames.
        check_flag = 1 if collectivecheck.collective_check_enabled() else 0
        with obs.span(
            "gramian.sparse.allgather",
            step=step,
            phase="header",
            stream=exchange.stream,
            processes=world,
        ):
            exchange.post_header(
                step,
                np.array(
                    [code, k_max, rows, num, nnz, nwin, check_flag],
                    np.int64,
                ),
            )
            peer_info = exchange.gather_headers(step, 7)
        failed = [
            i for i, row in enumerate(peer_info) if int(row[0]) == -2
        ]
        if failed:
            _note_pod_sync("producer-error")
            # exc is None on healthy peers — `from None` is a no-op
            # there.
            raise RuntimeError(
                "carrier stream failed on process(es) "
                f"{failed}; raising on every process together (a "
                "one-sided raise would strand peers in the next "
                "collective)"
            ) from exc
        live = peer_info[peer_info[:, 0] >= 0]
        if live.size == 0:
            _note_pod_sync("drained")
            exchange.close()
            return None
        routes = sorted({int(c) for c in live[:, 0]})
        if len(routes) > 1:
            _note_pod_sync("route-divergence")
            per_proc = {
                i: _ROUTE_OF_CODE[int(row[0])]
                for i, row in enumerate(peer_info)
                if int(row[0]) >= 0
            }
            raise ValueError(
                "sparse pod streams disagree on the density route "
                f"for the same step: {per_proc}; the route is a "
                "per-window GLOBAL decision (both routes are "
                "collective programs) — pin "
                "--sparse-density-threshold to one side for "
                "heterogeneous cohorts"
            )
        nums = sorted({int(n) for n in live[:, 3]})
        if len(nums) > 1:
            # The dtype is DERIVED from the agreed route today, so this
            # can only fire on a version-skewed pod (hosts running
            # different code deriving different payload dtypes for the
            # same route) — the cross-version guard, not a runtime data
            # check.
            _note_pod_sync("dtype-divergence")
            raise ValueError(
                "sparse pod payload dtypes diverged in the same "
                f"step: {[_dtype_name(n) for n in nums]}; every "
                "host must stream one payload dtype (the dtype "
                "derives from the agreed route — divergence means "
                "a version-skewed pod)"
            )
        route = _ROUTE_OF_CODE[routes[0]]
        g_rows = _pad_rows_for_scan(int(live[:, 2].max()))
        # Derived step geometry — the values every process computes
        # LOCALLY from the gathered (identical) headers: the carrier
        # bucket on scatter steps, the pow2 panel width on dense ones.
        # Pure arithmetic on agreed ints, so it runs outside the
        # payload try; pulled ahead of payload construction so the
        # collective-check digest can cover it before any payload
        # bytes move.
        bucket = 0
        g_dense = 0
        payload_num = nums[0]  # the agreed payload dtype (checked above)
        if route == "scatter":
            bucket = _carrier_bucket(int(live[:, 1].max()))
            geometry = (g_rows, bucket, world, n_padded, payload_num)
        else:
            g_dense = dense_panel_width(int(live[:, 2].max()), dense_width)
            geometry = (g_dense, v_div, world, n_padded, payload_num)
        # Collective-congruence backstop: every LIVE process enabled it
        # (agreed, from the gathered flag column — a drained process
        # evaluates the same gathered predicate and participates in the
        # exchange regardless of its own env, so the decision stays
        # congruent) → exchange a digest of this step's derived
        # (op, geometry) sequence and raise on every process together
        # at the first divergent step.
        if bool((live[:, 6] == 1).all()):
            digest = collectivecheck.step_digest(
                exchange.stream,
                step,
                [("header", (world, 7)), (route, geometry)],
            )
            with obs.span(
                "gramian.sparse.allgather",
                step=step,
                phase="check",
                stream=exchange.stream,
                processes=world,
            ):
                exchange.post_check(step, digest)
                digests = exchange.gather_checks(step)
            collectivecheck.verify_step_digests(step, digests, digest)
        # Local payload construction is host numpy work (carrier
        # padding, densify/pack) that can fail one-sided — e.g.
        # MemoryError on the densify at biobank widths — AFTER the
        # header sync has committed every peer to this step, so it runs
        # under its own try and the confirm exchange agrees success
        # before any payload moves: the same all-raise-together
        # discipline, per in-flight slot.
        payload_exc = None
        local = None
        try:
            if route == "scatter":
                if gang:
                    gidx = np.concatenate(
                        [idx for idx, _ in gang]
                    )
                    glens = np.concatenate(
                        [lens for _, lens in gang]
                    )
                    local = padded_carrier_matrix(
                        gidx,
                        glens,
                        sentinel=n_padded,
                        n_rows=g_rows,
                        k_bucket=bucket,
                    )
                # Drained stream: nothing to post — every peer
                # synthesizes this process's inert all-sentinel block
                # locally from its −1 header (zero bytes moved).
            else:
                # g_dense is the pow2 panel bucket of the GLOBAL max
                # row count (identical gathered data on every process
                # ⇒ identical width), derived above with the step
                # geometry: tail/small windows no longer pay the full
                # block width in inert MXU columns.
                if gang:
                    xb = _densify_window(
                        gang[0][0], gang[0][1], n_samples, g_dense
                    )
                else:
                    xb = np.zeros((n_samples, g_dense), dtype=np.int8)
                if n_padded != n_samples:
                    xb = np.pad(
                        xb, ((0, n_padded - n_samples), (0, 0))
                    )
                xp = pack_indicator_block(xb)
                cols = round_up_multiple(xp.shape[1], v_div)
                if cols != xp.shape[1]:
                    # Zero bytes unpack to inert zero columns; every
                    # process derives the same width from the same
                    # gathered header, so the global shape agrees.
                    xp = np.pad(xp, ((0, 0), (0, cols - xp.shape[1])))
                local = xp
        except Exception as e:  # noqa: BLE001 — synced just below
            payload_exc = e
        with obs.span(
            "gramian.sparse.allgather",
            step=step,
            phase="confirm",
            stream=exchange.stream,
            processes=world,
        ):
            exchange.post_confirm(step, payload_exc is None)
            confirm = exchange.gather_confirms(step)
        bad = [i for i, v in enumerate(confirm) if int(v) == -2]
        if bad:
            _note_pod_sync("producer-error")
            raise RuntimeError(
                "carrier payload construction failed on "
                f"process(es) {bad}; raising on every process "
                "together (a one-sided raise would strand peers "
                "in the payload collective)"
            ) from payload_exc
        gathered = None
        if route == "scatter":
            with obs.span(
                "gramian.sparse.allgather",
                step=step,
                phase="carrier",
                stream=exchange.stream,
                processes=world,
            ):
                if local is not None:
                    exchange.post_payload(step, local)
                parts = []
                for p in range(world):
                    if p == pid:
                        parts.append(
                            local
                            if local is not None
                            else np.full(
                                (g_rows, bucket), n_padded, np.int32
                            )
                        )
                    elif int(peer_info[p, 0]) >= 0:
                        parts.append(
                            exchange.get_payload(
                                step, p, (g_rows, bucket)
                            )
                        )
                    else:
                        # Drained peer: synthesize its inert
                        # all-sentinel block locally — zero bytes
                        # moved for a peer with nothing to say.
                        parts.append(
                            np.full(
                                (g_rows, bucket), n_padded, np.int32
                            )
                        )
                gathered = np.concatenate(parts, axis=0)
        _note_pod_sync("synced")
        _note_pod_gang(nwin)
        return PodSlot(
            step=step,
            route=route,
            gathered=gathered,
            local=local,
            nnz=nnz,
            variants=max(rows, 0),
            windows=nwin,
        )

    def _produce():
        step = state["step"]
        with obs.span(
            "gramian.sparse.slot",
            step=step,
            depth=pipeline_depth,
            stream=exchange.stream,
            processes=world,
        ):
            slot = _produce_step(step)
        state["step"] = step + 1
        return slot

    # Failure-path discipline around the pipeline: a SYNCHRONIZED
    # protocol failure (raised by next() — every process raised at the
    # same frame boundary, pipes provably clean) propagates as-is and
    # the mesh stays reusable (the chaos suite runs failing streams
    # back-to-back). A ONE-SIDED abandonment — this process's device
    # staging raising, or the consumer's loop body dying (lands here
    # as GeneratorExit at the yield) — leaves the sync thread possibly
    # blocked mid-read with peers' frames still on the pipes, so the
    # mesh is poisoned: a later stream must fail loudly instead of
    # desyncing on garbage (pod recovery = fail-stop + relaunch).
    pipe_iter = iter(SlotPipeline(_produce, pipeline_depth))
    while True:
        try:
            slot = next(pipe_iter)
        except StopIteration:
            return
        try:
            if slot.route == "scatter":
                gathered = slot.gathered
                payload = jax.make_array_from_callback(
                    gathered.shape,
                    idx_sharding,
                    lambda sl, _g=gathered: _g[sl],
                )
            else:
                payload = jax.make_array_from_process_local_data(
                    x_sharding, slot.local
                )
            item = (
                slot.route,
                payload,
                slot.nnz,
                slot.variants,
                slot.step,
                slot.windows,
                exchange.stream,
            )
        except BaseException:
            exchange.poison()
            raise
        try:
            yield item
        except BaseException:
            exchange.poison()
            raise


def sparse_sharded_gramian_blockwise(
    windows,
    n_samples: int,
    mesh: Mesh,
    accum_dtype=jnp.float32,
    density_threshold=None,
    block_variants=None,
    compute_dtype=None,
    pipeline_depth: int = 2,
    coalesce_variants=None,
):
    """Stream CSR carrier windows into a mesh-sharded (tiled) Gramian.

    The biobank-scale composition (ROADMAP item 2): G lives 2-D
    block-sharded ``P(data, model)`` over the mesh grid — each device
    owns one ``(N/rows, N/cols)`` tile, so N×N never materializes on any
    single device — and each window accumulates WITHOUT densifying:

    - sparse windows (density below the threshold,
      :func:`spark_examples_tpu.ops.sparse.window_route`) scatter their
      padded carrier matrix into every tile under ``shard_map``: each
      device re-bases the global sample indices into its tile frame,
      maps out-of-tile carriers to an out-of-bounds sentinel, and the
      OOB-drop scatter accumulates exactly the pairs that land in its
      tile. No collective at all — the carrier matrix is replicated
      (it is ~d·N·V_blk integers, tiny next to the dense block it
      replaces) and tiles partition the pair space.
    - dense windows densify + bit-pack onto the existing MXU
      accumulator with G kept in the same tiled layout (GSPMD gathers
      the block columns; G never moves — the
      :func:`sharded_gramian_blockwise_global` layout argument).

    Both routes add exact integer counts, so the result is bit-identical
    to the dense reference at any mesh shape and any window order
    (pinned by tests). On a single-controller mesh ingest is restricted
    to this process's sample-range bounds first
    (:func:`addressable_sample_bounds`) — the per-host sample-range
    contract; there the bounds are the full range and the restriction
    is a no-op.

    PROCESS-SPANNING meshes run the per-step carrier-allgather protocol
    (:func:`_synced_carrier_stream`, the sparse twin of
    :func:`_synced_block_stream`): each process feeds its own variant
    windows; per window a header allgather agrees liveness, the global
    carrier width bucket, and the density route (divergence raises on
    every process together — never a one-sided deadlock), then the
    padded carrier matrices allgather cross-host (~d·N·V_blk sparse
    integers per window instead of dense packed panels) and every
    device re-bases the concatenated global matrix into its tile frame
    for the same OOB-drop scatter — zero new N×N anywhere. Dense-route
    windows of a mixed stream ride the existing packed pod collective.
    Pod ingest ships FULL sample-range windows (each host is the source
    of its variants for every peer's tiles), so the sample-range
    restriction applies only to single-controller meshes.
    """
    from spark_examples_tpu import obs
    from spark_examples_tpu.arrays.blocks import (
        DEFAULT_BLOCK_VARIANTS,
        _check_indices,
        _densify_window,
        restrict_window_to_sample_range,
        round_up_multiple,
    )
    from spark_examples_tpu.ops.sparse import (
        DEFAULT_SPARSE_DENSITY_THRESHOLD,
        _note_window,
        _pad_rows_for_scan,
        dense_panel_width,
        padded_carrier_matrix,
        window_route,
    )

    if density_threshold is None:
        density_threshold = DEFAULT_SPARSE_DENSITY_THRESHOLD
    d_axis, m_axis = _mesh_axes(mesh)
    g_sharding = NamedSharding(mesh, P(d_axis, m_axis))
    n_padded = round_up_multiple(
        n_samples, _axis_product(mesh, g_sharding.spec)
    )
    grid_rows = mesh.shape[d_axis]
    grid_cols = mesh.shape[m_axis] if m_axis is not None else 1
    tile_rows = n_padded // grid_rows
    tile_cols = n_padded // grid_cols
    spans = _mesh_spans_processes(mesh)
    compute_dtype = resolve_gramian_compute_dtype(
        jnp.int8, accum_dtype, compute_dtype
    )
    width = block_variants or DEFAULT_BLOCK_VARIANTS
    from spark_examples_tpu.ops.scatter_kernel import resolve_scatter_path

    # One scan-vs-Pallas resolution per stream, OUTSIDE any trace; part
    # of the executable cache key so the env switch is honored per run.
    scatter_path = resolve_scatter_path(
        (tile_rows, tile_cols), np.dtype(accum_dtype)
    )
    # Square pod tile grids skip the strictly-lower (transpose-
    # redundant) tiles during accumulation and mirror once at the end —
    # see _sparse_tile_kernels. Pod-only: the host-local path's G may
    # feed further host-side merges (allreduce_gramian) per-tile.
    mirror = (
        spans and grid_rows == grid_cols and grid_rows > 1
    )
    scatter, _accum_dense, _accum_dense_pod, _mirror_fill = (
        _sparse_tile_kernels(
            mesh,
            d_axis,
            m_axis,
            n_padded,
            tile_rows,
            tile_cols,
            np.dtype(accum_dtype).name,
            np.dtype(compute_dtype).name,
            scatter_path,
            mirror,
        )
    )
    idx_sharding = NamedSharding(mesh, P(None, None))
    g = jax.device_put(
        jnp.zeros((n_padded, n_padded), dtype=accum_dtype), g_sharding
    )
    with obs.span("gramian.sparse.accumulate", n=n_samples, sharded=True):
        if spans:
            # Pod mode: every step is a collective device program, so
            # windows arrive through the pipelined synced carrier
            # stream — dense pod panels use the variant-axis-over-
            # everything layout and the explicit packed-allgather tile
            # program (_tile_dense_pod).
            x_sharding = NamedSharding(
                mesh, P(None, tuple(mesh.axis_names))
            )
            v_div = _axis_product(mesh, P(tuple(mesh.axis_names)))
            stream = _synced_carrier_stream(
                windows,
                n_samples,
                n_padded,
                mesh,
                density_threshold,
                width,
                v_div,
                x_sharding,
                idx_sharding,
                pipeline_depth=pipeline_depth,
                coalesce_variants=coalesce_variants,
            )
            for (
                route,
                payload,
                nnz,
                n_variants,
                step,
                n_win,
                stream_id,
            ) in stream:
                with obs.span(
                    "gramian.sparse.window",
                    route=route,
                    nnz=nnz,
                    variants=n_variants,
                    step=step,
                    stream=stream_id,
                    windows=n_win,
                ):
                    if route == "scatter":
                        g = scatter(g, payload)
                    else:
                        g = _accum_dense_pod(g, payload)
                _note_window(route, nnz, count=n_win)
            if _mirror_fill is not None:
                # One tile-swap ppermute + transpose reconstructs the
                # skipped strictly-lower tiles — exact copies, so G
                # stays bit-identical to the full computation.
                g = _mirror_fill(g)
        else:
            x_sharding = NamedSharding(mesh, P(d_axis, None))
            lo, hi = addressable_sample_bounds(
                mesh, g_sharding, n_padded
            )
            for window_idx, lens in windows:
                lens = np.asarray(lens)
                _check_indices(np.asarray(window_idx), n_samples)
                window_idx, lens = restrict_window_to_sample_range(
                    window_idx, lens, lo, hi
                )
                route = window_route(lens, n_samples, density_threshold)
                nnz = int(lens.sum())
                with obs.span(
                    "gramian.sparse.window",
                    route=route,
                    nnz=nnz,
                    variants=int(lens.size),
                ):
                    if route == "scatter":
                        idx = padded_carrier_matrix(
                            window_idx,
                            lens,
                            sentinel=n_padded,
                            n_rows=_pad_rows_for_scan(lens.size),
                        )
                        g = scatter(g, jax.device_put(idx, idx_sharding))
                    else:
                        dense_width = dense_panel_width(
                            int(lens.size), width
                        )
                        xb = _densify_window(
                            window_idx, lens, n_samples, dense_width
                        )
                        if n_padded != n_samples:
                            xb = np.pad(
                                xb, ((0, n_padded - n_samples), (0, 0))
                            )
                        xp = pack_indicator_block(xb)
                        g = _accum_dense(
                            g, jax.device_put(xp, x_sharding)
                        )
                _note_window(route, nnz)
    if n_padded == n_samples:
        return g
    return _trim_square(g, n_samples)


def topk_eig_randomized(
    c,
    k: int,
    oversample: int = DEFAULT_RANDOMIZED_OVERSAMPLE,
    iters: int = 30,
    seed: int = 0,
    mesh: Mesh = None,
    timer=None,
    gap_warn_ratio: float = 0.95,
    tol: float = None,
    check_every: int = 5,
):
    """Top-|λ| eigenpairs of symmetric C by randomized subspace iteration.

    The sharded-eig path for N where a dense ``eigh`` is infeasible
    (SURVEY.md §7 hard-parts #3): every O(N²) op is a matmul against an
    (N, k+p) panel, which GSPMD shards with C; the per-iteration QR runs on
    the small replicated panel. Subspace iteration on C converges to the
    invariant subspace of the largest-|λ| eigenvalues (signs recovered via
    Rayleigh quotients), which is exactly the MLlib |λ|-ordering
    (see :mod:`spark_examples_tpu.ops.pcoa`).

    Returns ``(vecs (N,k), vals (k,))`` ordered by |λ| descending, signs
    normalized.

    Accuracy: on realistic PCoA spectra (population-structure cohorts have
    a few dominant eigenvalues over a long tail) the subspace converges to
    ~2e-7 max coordinate error vs dense ``eigh`` within 10 iterations at
    N=2048 (measured; see tests). The 30-iteration default is headroom for
    flatter spectra; only near-degenerate λ₁≈λ₂ pairs need more.

    Degeneracy is detected, not silent: when |λ_{k+1}|/|λ_k| exceeds
    ``gap_warn_ratio`` the returned subspace is well-converged but any
    basis *within* a near-degenerate pair is rotation-ambiguous — for a
    dense ``eigh`` just as much as for this method (a weakly structured
    cohort has no well-defined PC2). A :class:`SpectralGapWarning` fires
    with the ratio, and the ratio lands in the stage-timer report when a
    ``timer`` is passed. The Ritz values needed for the check come free
    from the oversampled panel.

    ``tol`` (opt-in) makes the iteration count adaptive: the power sweep
    runs in chunks of ``check_every`` under ``lax.while_loop``, stopping
    once every top-k Ritz pair's relative residual ``‖C·v − λ·v‖/|λ|``
    drops below ``tol``, or at the hard cap ``iters`` (rounded up to a
    whole chunk). The residual is the standard eigenpair criterion — it
    bounds eigenvector error at O(tol / gap), which is honest where
    Ritz-value stagnation is not (values converge at the square of the
    vector rate). The check reuses the chunk's own ``C @ q`` product, so
    its marginal cost is one power-iteration-equivalent per chunk, and
    the final Rayleigh–Ritz reuses the last chunk's small matrix rather
    than recomputing the O(N²·p) product. The chunked sweep applies the
    same operations in the same order as the fixed path, so an
    unconverged adaptive run (``tol=0``, ``iters`` a chunk multiple)
    yields the fixed path's subspace; on sharp population-structure
    spectra convergence lands well under the cap — pure chip time saved
    at stress N. The iteration count used lands in the stage-timer
    report.
    """
    n = c.shape[0]
    # The k+1-values convention lives in ONE helper (ops/pcoa.py): the
    # panel must carry a Ritz value past index k-1 or the spectral-gap
    # check silently never fires and a flat-spectrum cohort's ambiguity
    # goes unreported.
    p = randomized_panel_width(n, k, oversample)
    q0 = jax.random.normal(jax.random.PRNGKey(seed), (n, p), dtype=c.dtype)
    if mesh is not None and jax.process_count() > 1:
        # Multi-controller: the panel must be a global (replicated) array
        # to enter a jit alongside the process-spanning C — every process
        # derives the identical panel from the same key.
        host_q0 = np.asarray(q0)
        q0 = jax.make_array_from_callback(
            host_q0.shape,
            NamedSharding(mesh, P(None, None)),
            lambda idx: host_q0[idx],
        )

    def _ritz(c, q):
        # Rayleigh–Ritz on the current subspace; (p, p) stays small.
        b = q.T @ (c @ q)
        w, u = jnp.linalg.eigh(b)
        order = jnp.argsort(-jnp.abs(w))
        return q @ u[:, order], w[order]

    def _sweep(c, q, length):
        def body(q, _):
            y = c @ q  # the only O(N²) op — sharded with C
            q, _ = jnp.linalg.qr(y)
            return q, None

        q, _ = jax.lax.scan(body, q, None, length=length)
        return q

    @partial(jax.jit, static_argnames=("iters",))
    def _run(c, q, iters):
        return _ritz(c, _sweep(c, q, iters))

    @partial(jax.jit, static_argnames=("max_iters", "chunk"))
    def _run_adaptive(c, q, max_iters, chunk):
        tiny = jnp.finfo(c.dtype).tiny

        def cond(state):
            _, _, it, converged = state
            return jnp.logical_and(~converged, it < max_iters)

        def body(state):
            q, _, it, _ = state
            q = _sweep(c, q, chunk)
            y = c @ q  # reused: residual check AND the final Ritz matrix
            b = q.T @ y
            w, u = jnp.linalg.eigh(b)
            order = jnp.argsort(-jnp.abs(w))
            uk, wk = u[:, order[:k]], w[order[:k]]
            # Standard eigenpair residual per top-k Ritz pair:
            # ‖C v − λ v‖ with v = q·u, C v = y·u — no extra O(N²) work.
            rk = y @ uk - (q @ uk) * wk
            rel = jnp.max(
                jnp.linalg.norm(rk, axis=0)
                / jnp.maximum(jnp.abs(wk), tiny)
            )
            return q, b, it + chunk, rel < tol

        q, b, used, _ = jax.lax.while_loop(
            cond,
            body,
            (
                q,
                jnp.zeros((q.shape[1], q.shape[1]), c.dtype),
                jnp.int32(0),
                jnp.asarray(False),
            ),
        )
        # b is the last chunk's q.T @ (c @ q): Rayleigh–Ritz without
        # recomputing the O(N²·p) product.
        w, u = jnp.linalg.eigh(b)
        order = jnp.argsort(-jnp.abs(w))
        return q @ u[:, order], w[order], used

    if tol is not None:
        chunk = max(1, min(check_every, iters))
        vecs, vals, used = _run_adaptive(c, q0, iters, chunk)
        if timer is not None:
            timer.note(
                f"randomized eig: {int(used)}/{iters} iterations "
                f"(tol={tol:g})"
            )
    else:
        vecs, vals = _run(c, q0, iters)
    if mesh is not None and jax.process_count() > 1:
        # The (N, k+p) panel result is small even at stress N; replicate it
        # so hosts can read coordinates without touching the sharded C.
        rep = NamedSharding(mesh, P(None, None))
        vecs = jax.jit(lambda a: a, out_shardings=rep)(vecs)
        vals = jax.jit(
            lambda a: a, out_shardings=NamedSharding(mesh, P(None))
        )(vals)
    check_spectral_gap(np.asarray(vals), k, gap_warn_ratio, timer)
    return normalize_eigvec_signs(vecs[:, :k]), vals[:k]


def sharded_pcoa(
    g,
    k: int,
    mesh: Mesh,
    dense_eigh_limit: int = 8192,
    timer=None,
    eig_tol: float = None,
):
    """Center + top-k eigenvectors of a (possibly mesh-sharded) Gramian.

    Small N: gather the centered matrix and run dense ``eigh`` (exact, the
    replicated-eigh fallback of SURVEY.md §7). Large N: keep C sharded and
    use randomized subspace iteration — at the stress scale C is never
    materialized on any single device or host.
    """
    c = jax.jit(double_center)(g)
    n = c.shape[0]
    if n <= dense_eigh_limit:
        if not c.is_fully_addressable:
            # Process-spanning shards: replicate through a collective jit
            # (affordable by definition at dense-eigh N) so the host can
            # read it.
            c = jax.jit(
                lambda a: a, out_shardings=NamedSharding(mesh, P(None, None))
            )(c)
        c = jax.device_put(np.asarray(c))
        from spark_examples_tpu.ops.pcoa import (
            principal_components,
            topk_with_gap_check,
        )

        # One extra eigenpair so the gap past k is checkable — dense eigh
        # is exactly as rotation-ambiguous on a flat spectrum as the
        # randomized path, so it gets the same loud degeneracy detection.
        return topk_with_gap_check(
            lambda kk: principal_components(c, kk), k, n, timer=timer
        )
    return topk_eig_randomized(c, k, mesh=mesh, timer=timer, tol=eig_tol)


# -- Gramian-free sketch panels (ops/sketch.py's mesh half) ------------------


def _replicated_np(a) -> np.ndarray:
    """Host copy of a (possibly process-spanning) fully-replicated
    array. Every process holds the whole value under P(None, ...), so
    the local shard IS the global array — no collective needed."""
    if getattr(a, "is_fully_addressable", True):
        return np.asarray(a)
    return np.asarray(a.addressable_shards[0].data)


@functools.lru_cache(maxsize=None)
def _sketch_pod_dense_kernel(mesh: Mesh):
    """The dense-route pod step for the SKETCH panel as one explicit
    shard_map program: each device unpacks only ITS packed variant
    columns (the same variant-axis-over-everything payload layout the
    Gramian pod step ships), computes its local
    ``X_loc · (X_locᵀ · Ω̃)`` contribution, and one psum over every
    mesh axis replicates the window's full update — no (N, V) unpack
    broadcast anywhere (the GSPMD-rematerialization lesson of the
    Gramian's `_tile_dense_pod`)."""
    all_axes = tuple(mesh.axis_names)

    def _step(y_loc, xp_loc, om_loc):
        xb = unpack_indicator_block(
            xp_loc, 8 * xp_loc.shape[1]
        ).astype(y_loc.dtype)
        contrib = xb @ (xb.T @ om_loc)
        return y_loc + jax.lax.psum(contrib, all_axes)

    return jax.jit(
        _shard_map(
            _step,
            mesh=mesh,
            in_specs=(P(None, None), P(None, all_axes), P(None, None)),
            out_specs=P(None, None),
            check_vma=False,
        ),
        donate_argnums=(0,),
    )


def sketch_tsqr(y, mesh: Mesh):
    """Tall-skinny QR of a mesh-resident (n_padded, l) panel — the
    sketch finish's pod-scale factorization (ROADMAP item 2's
    "TSQR + small eig" half).

    Classic two-level TSQR under ``shard_map`` over EVERY mesh axis
    flattened: per-device thin QR of the local row block, an
    ``all_gather`` of the (l, l) R factors, one replicated QR of the
    stacked (D·l, l) ladder, and each device composes its Q block with
    its slice of the second-level Q. Returns ``(q, r)``: q row-sharded
    over the flattened device axis, r replicated. Requires
    ``n_padded / device_count ≥ l`` (callers fall back to a host QR
    below that — the panel is tiny there by definition)."""
    axes = tuple(mesh.axis_names)
    n_padded, l = int(y.shape[0]), int(y.shape[1])
    flat = P(axes, None)
    flat_sharding = NamedSharding(mesh, flat)

    def _local(y_loc):
        q1, r1 = jnp.linalg.qr(y_loc)
        rs = jax.lax.all_gather(r1, axes, axis=0, tiled=True)
        q2, r = jnp.linalg.qr(rs)
        # Flattened device index composed per-axis: tuple-valued
        # axis_index is newer than the jax floor this tree supports.
        i = jnp.int32(0)
        for name in axes:
            i = i * mesh.shape[name] + jax.lax.axis_index(name)
        q2_i = jax.lax.dynamic_slice(q2, (i * l, 0), (l, l))
        return q1 @ q2_i, r

    fn = jax.jit(
        _shard_map(
            _local,
            mesh=mesh,
            in_specs=flat,
            out_specs=(flat, P(None, None)),
            check_vma=False,
        )
    )
    y_flat = jax.jit(lambda a: a, out_shardings=flat_sharding)(y)
    return fn(y_flat)


def sharded_sketch_panel(
    windows_factory,
    n_samples: int,
    k: int,
    mesh: Mesh,
    oversample=None,
    power_iters=None,
    seed: int = 0,
    density_threshold=None,
    block_variants=None,
    pipeline_depth: int = 2,
    coalesce_variants=None,
):
    """Stream CSR carrier windows into a mesh-replicated (N, k+p)
    sketch panel — the ``--pca-mode sketch`` twin of
    :func:`sparse_sharded_gramian_blockwise` that never materializes
    an N×N tile anywhere (ROADMAP item 2's million-sample row).

    The panel is O(N·(k+p)) f32, so unlike G it REPLICATES over the
    mesh (P(None, None)); what the mesh buys is the window machinery —
    and the TSQR finish. Topologies:

    - single-controller mesh: host window loop, full sample range (the
      sketch updates every row per window, so the Gramian path's
      sample-range restriction must NOT apply);
    - process-spanning pod: the per-step carrier-allgather protocol
      (:func:`_synced_carrier_stream`) unchanged — headers, fencing,
      route sync, coalesced gangs, and collective-check digests all
      extend to sketch steps for free; scatter payloads feed the same
      OOB-drop panel scatter, dense payloads the explicit
      psum program (:func:`_sketch_pod_dense_kernel`);
    - host-local mesh on a multi-controller run: each host accumulates
      its manifest slice's partial panel and the partials merge over
      DCN (the dense tiers' allreduce shape, but on (N, l) panels).

    ``windows_factory`` returns a fresh iterator per call — each
    ``--sketch-power-iters`` pass re-streams the cohort with
    Ω ← orth(Y). Returns an :class:`~spark_examples_tpu.ops.sketch.
    SketchPanel` with host f64 panels (n_padded rows) and ``mesh`` set
    so the finish routes through :func:`sharded_sketch_finish`.
    """
    from spark_examples_tpu import obs
    from spark_examples_tpu.arrays.blocks import (
        DEFAULT_BLOCK_VARIANTS,
        _check_indices,
        _densify_window,
        round_up_multiple,
    )
    from spark_examples_tpu.ops.pcoa import (
        DEFAULT_SKETCH_POWER_ITERS,
        randomized_panel_width,
    )
    from spark_examples_tpu.ops.sketch import (
        _note_sketch_window,
        _sketch_dense_update,
        _sketch_scatter_update,
        gaussian_test_matrix,
        sketch_host_bytes,
    )
    from spark_examples_tpu.ops.sparse import (
        DEFAULT_SPARSE_DENSITY_THRESHOLD,
        _pad_rows_for_scan,
        dense_panel_width,
        padded_carrier_matrix,
        window_route,
    )

    if density_threshold is None:
        density_threshold = DEFAULT_SPARSE_DENSITY_THRESHOLD
    if oversample is None:
        oversample = DEFAULT_RANDOMIZED_OVERSAMPLE
    if power_iters is None:
        power_iters = DEFAULT_SKETCH_POWER_ITERS
    width = block_variants or DEFAULT_BLOCK_VARIANTS
    l = randomized_panel_width(n_samples, k, oversample)
    all_axes = tuple(mesh.axis_names)
    n_padded = round_up_multiple(
        n_samples, _axis_product(mesh, P(all_axes))
    )
    spans = _mesh_spans_processes(mesh)
    rep = NamedSharding(mesh, P(None, None))
    omega_cur = gaussian_test_matrix(n_samples, l, seed)
    row_sums = np.zeros(n_samples, dtype=np.float64)
    y_host = None
    for p in range(power_iters + 1):
        first = p == 0
        aug = _sketch_aug_padded(omega_cur, n_samples, n_padded, first)
        om_dev = jax.device_put(aug, rep)
        y = jax.device_put(
            jnp.zeros((n_padded, l + 1), dtype=jnp.float32), rep
        )
        with obs.span(
            "gramian.sketch.accumulate",
            n=n_samples,
            l=l,
            sharded=True,
            sketch_pass=p,
        ):
            if spans:
                x_sharding = NamedSharding(mesh, P(None, all_axes))
                v_div = _axis_product(mesh, P(all_axes))
                idx_sharding = NamedSharding(mesh, P(None, None))
                dense_pod = _sketch_pod_dense_kernel(mesh)
                stream = _synced_carrier_stream(
                    windows_factory(),
                    n_samples,
                    n_padded,
                    mesh,
                    density_threshold,
                    width,
                    v_div,
                    x_sharding,
                    idx_sharding,
                    pipeline_depth=pipeline_depth,
                    coalesce_variants=coalesce_variants,
                )
                for (
                    route,
                    payload,
                    nnz,
                    n_variants,
                    step,
                    n_win,
                    stream_id,
                ) in stream:
                    with obs.span(
                        "gramian.sketch.window",
                        route=route,
                        nnz=nnz,
                        variants=n_variants,
                        step=step,
                        stream=stream_id,
                        windows=n_win,
                    ):
                        if route == "scatter":
                            y = _sketch_scatter_update(
                                y, om_dev, payload
                            )
                        else:
                            y = dense_pod(y, payload, om_dev)
                    _note_sketch_window(route, count=n_win)
            else:
                for window_idx, lens in windows_factory():
                    lens = np.asarray(lens)
                    _check_indices(
                        np.asarray(window_idx), n_samples
                    )
                    route = window_route(
                        lens, n_samples, density_threshold
                    )
                    nnz = int(lens.sum())
                    with obs.span(
                        "gramian.sketch.window",
                        route=route,
                        nnz=nnz,
                        variants=int(lens.size),
                    ):
                        if route == "scatter":
                            idx = padded_carrier_matrix(
                                window_idx,
                                lens,
                                sentinel=n_padded,
                                n_rows=_pad_rows_for_scan(
                                    lens.size
                                ),
                            )
                            y = _sketch_scatter_update(
                                y,
                                om_dev,
                                jax.device_put(idx, rep),
                            )
                        else:
                            xb = _densify_window(
                                window_idx,
                                lens,
                                n_samples,
                                dense_panel_width(
                                    int(lens.size), width
                                ),
                            )
                            if n_padded != n_samples:
                                xb = np.pad(
                                    xb,
                                    (
                                        (0, n_padded - n_samples),
                                        (0, 0),
                                    ),
                                )
                            y = _sketch_dense_update(
                                y,
                                om_dev,
                                jax.device_put(
                                    pack_indicator_block(xb), rep
                                ),
                            )
                    _note_sketch_window(route)
        y_np = _replicated_np(y).astype(np.float64)
        if not spans and jax.process_count() > 1:
            # Host-local mesh on a multi-controller run: each host fed
            # only its manifest slice — merge the partial panels.
            from spark_examples_tpu.parallel.distributed import (
                allreduce_gramian,
            )

            y_np = np.asarray(allreduce_gramian(y_np))
        if first:
            row_sums = y_np[:n_samples, -1].copy()
        y_host = y_np[:, :-1]
        y_host -= y_host[:n_samples].mean(axis=0, keepdims=True)
        y_host[n_samples:] = 0.0
        if p < power_iters:
            q, _ = np.linalg.qr(y_host[:n_samples])
            omega_cur = q.astype(np.float32)
    from spark_examples_tpu.ops.sketch import SketchPanel

    omega_final = np.zeros((n_padded, l), dtype=np.float64)
    omega_final[:n_samples] = omega_cur.astype(np.float64)
    omega_final[:n_samples] -= omega_final[:n_samples].mean(
        axis=0, keepdims=True
    )
    return SketchPanel(
        y=y_host,
        omega=omega_final,
        row_sums=row_sums,
        n=n_samples,
        k=k,
        l=l,
        seed=seed,
        power_iters=power_iters,
        mesh=mesh,
        host_peak_bytes=sketch_host_bytes(n_padded, l),
    )


def _sketch_aug_padded(
    omega: np.ndarray, n: int, n_padded: int, first: bool
) -> np.ndarray:
    """The streamed right-hand panel for mesh runs: centered Ω̃ over
    the n real rows, zero pad rows, plus the companion column (ones on
    the first pass — the row-sums/parity vector — zeros after, keeping
    one executable geometry across passes)."""
    aug = np.zeros((n_padded, omega.shape[1] + 1), dtype=np.float32)
    aug[:n, :-1] = omega - omega.mean(axis=0, keepdims=True)
    if first:
        aug[:n, -1] = 1.0
    return aug


def sharded_sketch_finish(panel, k: int):
    """The sketch Nyström finish on a mesh: device TSQR of the shifted
    panel (:func:`sketch_tsqr` over the pod), the (k+p)×(k+p) core on
    the host in f64, and one sharded matmul for the coordinates.
    Returns ``(coords (n_padded, l), vals (l,))`` — the caller
    (:func:`spark_examples_tpu.ops.sketch.sketch_eig`) trims, checks
    the spectral gap, and sign-normalizes."""
    from spark_examples_tpu.ops.sketch import _nystrom_core

    mesh = panel.mesh
    y, omega = panel.y, panel.omega
    norm = float(np.linalg.norm(y))
    if norm == 0.0:
        return np.zeros((panel.n, panel.l)), np.zeros(panel.l)
    nu = float(np.sqrt(panel.n) * np.finfo(np.float32).eps * norm)
    y_nu = y + nu * omega
    n_padded, l = y_nu.shape
    rows_loc = n_padded // _axis_product(
        mesh, P(tuple(mesh.axis_names))
    )
    b = omega.T @ y_nu
    if rows_loc >= l:
        q_dev, r_dev = sketch_tsqr(
            jax.device_put(
                y_nu.astype(np.float32),
                NamedSharding(mesh, P(None, None)),
            ),
            mesh,
        )
        r = _replicated_np(r_dev).astype(np.float64)
        u1, vals = _nystrom_core(r, b, nu)
        coords_dev = jax.jit(
            lambda qq, uu: qq @ uu,
            out_shardings=NamedSharding(mesh, P(None, None)),
        )(q_dev, jnp.asarray(u1.astype(np.float32)))
        coords = _replicated_np(coords_dev).astype(np.float64)
    else:
        # Fewer rows per device than panel columns: the TSQR local QR
        # shape contract breaks, and at that size the whole finish is
        # host change money.
        q, r = np.linalg.qr(y_nu)
        u1, vals = _nystrom_core(r, b, nu)
        coords = q @ u1
    return coords, vals
