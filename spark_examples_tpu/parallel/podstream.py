"""Host-side pod-sparse window exchange + the depth-D slot pipeline.

The pod-sparse protocol's per-window agreement (header, payload-confirm,
carrier payload) historically rode device collectives
(``multihost_utils.process_allgather``): every exchange was a device
program enqueued BEHIND the previous window's scatter on each device's
serial execution stream, so collective latency serialized against
scatter compute — the phase-barrier shape PR 9 removed from cold ingest,
now inside the pod stream (MULTICHIP_r06 pins the cost: ~8× off
single-controller at the same N). This module moves the agreement onto a
**persistent host-side TCP mesh between the pod's processes**: pure
socket IO, no device programs and no jaxlib calls, so a sync thread can
run window w+1's whole exchange while window w's scatter executes on
device. Three pieces:

- :class:`_PodSocketMesh` — the per-process singleton full mesh of
  peer sockets. Peer addresses bootstrap ONCE through the
  jax.distributed coordination-service KV store (the only jaxlib-client
  touch, made from the main thread before any pipelined work); after
  that every protocol byte flows over the sockets. This matters beyond
  latency: the coordination client is shared with jax internals — the
  gloo CPU-collective rendezvous and the compilation cache use it from
  XLA's own threads — and concurrent client calls from a second Python
  thread segfault jaxlib. The socket mesh keeps the sync thread off the
  client entirely. Sends run on tiny per-peer sender threads so a slow
  peer can never produce a mutual send-block deadlock; receives run on
  the sync thread in deterministic per-peer frame order (TCP preserves
  each peer's post order, and the protocol makes every receive's
  (stream, step, kind) predictable).
- :class:`PodWindowExchange` — one stream's framed post/gather API over
  the mesh (headers, confirms, payloads). Streams are opened in
  identical program order on every process — the same assumption every
  collective already makes — so a module-level counter names them
  consistently; frames carry (stream, step, kind) and a mismatch is a
  loud protocol error, never silent reordering.
- :class:`SlotPipeline` — the depth-D bounded pipeline: a daemon thread
  repeatedly calls a ``produce`` callback (one protocol step per call)
  and stages results into a bounded queue; the consumer iterates staged
  slots. A producer exception is re-raised in the consumer AT ITS SLOT
  POSITION — every process sees the same agreed stream order, so the
  raise lands on the same step everywhere (the all-raise-together
  discipline of the lockstep protocol, preserved per in-flight slot).

Synchronized-failure cleanliness: every failure the protocol raises
(producer −2 headers, payload-confirm −2, route/dtype divergence) is
detected from identical gathered data AFTER the same phase on every
process, so all peers stop at the same point in the frame sequence and
no socket is left holding half-read frames — the next stream starts on
clean pipes.

Peer DEATH (kill -9, OOM, host loss) is the one failure that cannot be
agreed from gathered data — the peer stops posting mid-sequence. The
hardened receive path converts it into the same shape: EOF/ECONNRESET
on an established socket raises :class:`PodPeerDeadError`, the gather
paths synthesize the dead peer's −2 header/confirm (the exact
producer-failure encoding every process already raises on together),
and the mesh tears its sockets down so every survivor detects within
one receive instead of one phase apart — all survivors raise at the
same slot. The mesh is poisoned afterwards: a pod minus a member is
fail-stop + relaunch, never a silent continue.
"""

from __future__ import annotations

import itertools
import queue
import socket
import struct
import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np

__all__ = [
    "POD_EXCHANGE_TIMEOUT_S",
    "PodPeerDeadError",
    "PodWindowExchange",
    "SlotPipeline",
    "coordination_client",
]

# Blocking-receive deadline for one protocol phase: generous (a peer may
# legitimately be deep in host ingest for its next window), but finite —
# a dead peer turns into a loud RuntimeError instead of the native
# collective's silent forever-hang. --collective-timeout's watchdog
# remains the tighter fail-stop story when configured.
POD_EXCHANGE_TIMEOUT_S = 1800.0

_STREAM_IDS = itertools.count()

# Frame kinds on the wire. _KIND_CHECK carries the optional
# collective-congruence digest (utils/collectivecheck): it is only ever
# posted when EVERY live process advertised the check in its step
# header, so a mixed-enablement pod never desyncs on unexpected frames.
_KIND_HEADER = 0
_KIND_CONFIRM = 1
_KIND_PAYLOAD = 2
_KIND_CHECK = 3

# stream (q), step (q), kind (B), byte length (q) — little-endian.
_FRAME = struct.Struct("<qqBq")


class PodPeerDeadError(RuntimeError):
    """An established peer socket died mid-protocol (EOF/ECONNRESET —
    the peer process was killed, OOMed, or its host vanished).

    Distinct from the generic protocol-desync/timeout RuntimeErrors so
    the gather paths can CONVERT it into the synchronized −2 failure
    shape every process already handles (producer-error semantics:
    raise together at the same slot) instead of each survivor hanging
    out its own receive deadline one phase apart. ``peer`` is the dead
    process index when known.
    """

    def __init__(self, message: str, peer: Optional[int] = None) -> None:
        super().__init__(message)
        self.peer = peer


def coordination_client() -> Any:
    """The jax.distributed coordination-service client, or ``None``.

    Present on every process of a multi-process jax run (it is what
    ``jax.distributed.initialize`` connects); ``None`` single-process.
    Used here ONLY for the one-time peer-address bootstrap, from the
    main thread — see the module docstring for why per-step traffic
    must stay off this client.
    """
    try:
        from jax._src import distributed

        return distributed.global_state.client
    except Exception:  # pragma: no cover — jax internals drift
        return None


def _local_ip() -> str:
    """The IP this host uses to reach the coordinator (UDP-connect
    trick — no packet is sent; IPv6 coordinator addresses are bracket-
    stripped and probed over AF_INET6). Falls back to the hostname's
    resolved address, then loopback (correct only for the
    single-machine pod-sim — a multi-host mesh that lands there fails
    the dial with connection-refused, surfaced loudly by setup)."""
    try:
        from jax._src import distributed

        coord = str(distributed.global_state.coordinator_address)
        host = coord.rsplit(":", 1)[0]
        family = socket.AF_INET
        if host.startswith("[") and host.endswith("]"):
            host, family = host[1:-1], socket.AF_INET6
        elif ":" in host:
            family = socket.AF_INET6
        s = socket.socket(family, socket.SOCK_DGRAM)
        try:
            s.connect((host, 1))
            return s.getsockname()[0]
        finally:
            s.close()
    except Exception:
        pass
    try:
        return socket.gethostbyname(socket.gethostname())
    except Exception:
        return "127.0.0.1"


class _PeerSender:
    """One peer's outbound frame queue + daemon sender thread.

    Sends must never run on the sync thread: with every process pushing
    payload frames to every peer before reading any, two full TCP
    buffers would deadlock the pod. The queue is unbounded but its depth
    is governed by the pipeline depth (a handful of frames)."""

    def __init__(self, sock: socket.socket, peer: int) -> None:
        self._sock = sock
        self._q: "queue.Queue[Optional[bytes]]" = queue.Queue()
        self._thread = threading.Thread(
            target=self._run,
            name=f"pod-exchange-send-{peer}",
            daemon=True,
        )
        self._thread.start()

    def send(self, frame: bytes) -> None:
        self._q.put(frame)

    def _run(self) -> None:
        while True:
            frame = self._q.get()
            if frame is None:
                return
            try:
                self._sock.sendall(frame)
            except OSError:
                # Peer gone (it raised and tore down, or died): the
                # receive side surfaces the loud error; sending more
                # is pointless but must not kill this process.
                return


class _PodSocketMesh:
    """Per-process full mesh of peer connections (module singleton).

    Connection setup: every process binds an ephemeral listening socket,
    publishes ``pod_exchange/addr/<pid>`` through the coordination KV
    store (the one-time bootstrap), then connects to every LOWER pid and
    accepts one connection from every HIGHER pid (identified by a hello
    byte) — one socket per unordered pair, used bidirectionally for the
    life of the process.
    """

    _instance: Optional["_PodSocketMesh"] = None
    _instance_lock = threading.Lock()

    def __init__(self, pid: int, world: int, timeout_s: float) -> None:
        self._pid = pid
        self._world = world
        self._timeout_s = timeout_s
        self._socks: Dict[int, socket.socket] = {}
        self._senders: Dict[int, _PeerSender] = {}
        self.poisoned = False
        self.poison_reason = ""
        self._connect(timeout_s)

    def poison(self) -> None:
        """Mark the mesh unusable: an ABANDONED stream (consumer died
        one-sided, e.g. an XLA error mid-dispatch) may have left its
        sync thread blocked mid-read and unread frames on the pipes —
        a later stream reusing these sockets would desync on garbage.
        Synchronized protocol failures (all peers raising at the same
        frame boundary) do NOT poison: the pipes are provably clean
        there and back-to-back streams are supported (the chaos suite
        runs exactly that). After poisoning, the pod's recovery
        contract is what it always was for one-sided death: fail-stop
        + relaunch (docs/ARCHITECTURE.md §5)."""
        self.poisoned = True
        self.poison_reason = (
            "an abandoned stream (one-sided consumer failure); the "
            "socket pipes may hold half-read frames"
        )

    @classmethod
    def instance(cls, timeout_s: float) -> Optional["_PodSocketMesh"]:
        with cls._instance_lock:
            if cls._instance is not None:
                if cls._instance.poisoned:
                    raise RuntimeError(
                        "pod exchange mesh was poisoned by "
                        + (
                            cls._instance.poison_reason
                            or "an abandoned stream"
                        )
                        + " — pod recovery is fail-stop + relaunch "
                        "(docs/ARCHITECTURE.md §5)"
                    )
                return cls._instance
            client = coordination_client()
            if client is None:
                return None
            import jax

            cls._instance = cls(
                jax.process_index(), jax.process_count(), timeout_s
            )
            return cls._instance

    def _connect(self, timeout_s: float) -> None:
        client = coordination_client()
        # The listener's family must match the ADVERTISED address: an
        # IPv6 fabric publishes a v6 address, and peers dialing it
        # against a v4-only listener would get connection-refused.
        ip = _local_ip()
        v6 = ":" in ip
        listener = socket.socket(
            socket.AF_INET6 if v6 else socket.AF_INET,
            socket.SOCK_STREAM,
        )
        listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        listener.bind(("::" if v6 else "0.0.0.0", 0))
        listener.listen(self._world)
        port = listener.getsockname()[1]
        addr = f"[{ip}]:{port}" if v6 else f"{ip}:{port}"
        client.key_value_set_bytes(
            f"pod_exchange/addr/{self._pid}", addr.encode()
        )
        timeout_ms = int(timeout_s * 1000)
        peers: Dict[int, Tuple[str, int]] = {}
        for p in range(self._world):
            if p == self._pid:
                continue
            raw = client.blocking_key_value_get_bytes(
                f"pod_exchange/addr/{p}", timeout_ms
            ).decode()
            host, pstr = raw.rsplit(":", 1)
            peers[p] = (host.strip("[]"), int(pstr))

        dial_exc: List[BaseException] = []

        def _dial() -> None:
            # Outbound side on a helper thread (pure sockets, no
            # jaxlib) so accept and connect cannot deadlock each other;
            # its exception is re-raised by the main thread below — a
            # refused/filtered peer must surface as ITS error, not as a
            # generic timeout after 30 minutes in accept().
            try:
                for p in sorted(peers):
                    if p >= self._pid:
                        continue
                    s = socket.create_connection(
                        peers[p], timeout=timeout_s
                    )
                    s.sendall(struct.pack("<q", self._pid))
                    self._socks[p] = s
            except BaseException as e:  # noqa: BLE001 — re-raised below
                dial_exc.append(e)

        dialer = threading.Thread(target=_dial, daemon=True)
        dialer.start()
        listener.settimeout(timeout_s)
        try:
            for _ in range(self._world - 1 - self._pid):
                conn, _ = listener.accept()
                # Accepted sockets are blocking regardless of the
                # listener's timeout; bound the hello read or a
                # half-open inbound connection hangs setup forever.
                conn.settimeout(timeout_s)
                (peer,) = struct.unpack(
                    "<q", self._recv_exact_raw(conn, 8)
                )
                self._socks[int(peer)] = conn
        finally:
            listener.close()
        dialer.join(timeout=timeout_s)
        if dial_exc:
            raise RuntimeError(
                "pod exchange mesh setup failed dialing a lower-pid "
                "peer (firewalled/NATed address, or the peer died "
                "before accepting?)"
            ) from dial_exc[0]
        missing = [
            p
            for p in range(self._world)
            if p != self._pid and p not in self._socks
        ]
        if missing:
            raise RuntimeError(
                f"pod exchange mesh setup failed: no connection to "
                f"process(es) {missing}"
            )
        for p, s in self._socks.items():
            s.settimeout(timeout_s)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._senders[p] = _PeerSender(s, p)

    @staticmethod
    def _recv_exact_raw(sock: socket.socket, n: int) -> bytes:
        buf = bytearray()
        while len(buf) < n:
            chunk = sock.recv(n - len(buf))
            if not chunk:
                raise PodPeerDeadError(
                    "pod exchange peer closed its connection "
                    "mid-protocol (peer process died?)"
                )
            buf.extend(chunk)
        return bytes(buf)

    def _peer_died(self, peer: int) -> None:
        """Peer-death cascade: poison the mesh (a member is gone — the
        pod's recovery contract is fail-stop + relaunch) and close every
        socket, so survivors blocked reading THIS process unblock with
        EOF immediately and convert the same way. Without the cascade,
        survivor A can detect the death one phase ahead of survivor B,
        stop posting, and leave B hanging out the full receive deadline
        waiting on A — the staggered-raise shape the −2 protocol
        exists to prevent."""
        self.poisoned = True
        self.poison_reason = (
            f"the death of pod process {peer} mid-protocol (mesh "
            "sockets torn down for the synchronized raise)"
        )
        for s in self._socks.values():
            try:
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                s.close()
            except OSError:
                pass

    def post(
        self, peer: int, stream: int, step: int, kind: int, body: bytes
    ) -> None:
        self._senders[peer].send(
            _FRAME.pack(stream, step, kind, len(body)) + body
        )

    def recv(
        self, peer: int, stream: int, step: int, kind: int
    ) -> bytes:
        """The next frame from ``peer`` — which the protocol guarantees
        is (stream, step, kind); anything else is version skew or a
        protocol bug and raises loudly."""
        sock = self._socks[peer]
        try:
            raw = self._recv_exact_raw(sock, _FRAME.size)
            got_stream, got_step, got_kind, length = _FRAME.unpack(raw)
            if (got_stream, got_step, got_kind) != (stream, step, kind):
                raise RuntimeError(
                    "pod exchange protocol desync with peer "
                    f"{peer}: expected (stream={stream}, step={step}, "
                    f"kind={kind}), got (stream={got_stream}, "
                    f"step={got_step}, kind={got_kind}) — "
                    "version-skewed pod or out-of-order stream "
                    "construction"
                )
            # Body read under the SAME attributed handler: a peer dying
            # mid-frame must surface with peer/stream/step context, not
            # as an anonymous socket.timeout half an hour later.
            return self._recv_exact_raw(sock, length) if length else b""
        except socket.timeout as e:
            raise RuntimeError(
                f"pod exchange timed out waiting for peer {peer} "
                f"(stream {stream} step {step} kind {kind}) after "
                f"{self._timeout_s:.0f}s; a lockstep collective would "
                "have hung here forever — check the peer's log"
            ) from e
        except (PodPeerDeadError, OSError) as e:
            # EOF or ECONNRESET/EPIPE on an ESTABLISHED socket: the
            # peer process died. Attribute it, cascade the teardown
            # (every survivor must detect within one recv, not one
            # phase later), and let the gather paths convert it into
            # the synchronized −2 failure shape.
            self._peer_died(peer)
            raise PodPeerDeadError(
                f"pod exchange peer {peer} died mid-protocol "
                f"(stream {stream} step {step} kind {kind}): {e}",
                peer=peer,
            ) from e


class PodWindowExchange:
    """One stream's post/gather API over the process socket mesh.

    Values are raw little-endian numpy bytes (headers int64, payloads
    int32 carrier matrices); shapes are derivable from the agreed
    header geometry, so no metadata rides the wire beyond the frame
    header.
    """

    def __init__(self, mesh: _PodSocketMesh, pid: int, world: int) -> None:
        self._mesh = mesh
        self._pid = pid
        self._world = world
        self._stream = next(_STREAM_IDS)
        # Own posted values, folded into gathers so no loopback socket
        # is needed (the allgather semantics include the local row).
        self._own_header = np.zeros(0, np.int64)
        self._own_confirm = np.int64(0)
        self._own_check = np.int64(0)
        # Wall-clock of this step's own header post: paired with each
        # peer header's arrival time in gather_headers to mint the
        # pod.exchange_ts instants merge_pod_trace.py estimates
        # per-peer clock offsets from (NTP midpoint method).
        self._last_send_unix = 0.0

    @property
    def stream(self) -> int:
        """This stream's process-lifetime-unique id (identical on every
        process — streams open in agreed program order). Rides the
        telemetry spans so trace analysis can scope per-stream (step
        numbers restart per stream)."""
        return self._stream

    @classmethod
    def open(
        cls, timeout_s: float = POD_EXCHANGE_TIMEOUT_S
    ) -> Optional["PodWindowExchange"]:
        """Exchange for this process, or ``None`` without a
        coordination client (single-process). Call from the MAIN
        thread: first use bootstraps the socket mesh through the
        coordination client, which must never race jax's own use of it
        (module docstring)."""
        import jax

        mesh = _PodSocketMesh.instance(timeout_s)
        if mesh is None:
            return None
        return cls(mesh, jax.process_index(), jax.process_count())

    def _post_all(self, step: int, kind: int, body: bytes) -> None:
        for p in range(self._world):
            if p != self._pid:
                self._mesh.post(p, self._stream, step, kind, body)

    def post_header(self, step: int, fields: np.ndarray) -> None:
        self._own_header = np.asarray(fields, np.int64)
        self._last_send_unix = time.time()
        self._post_all(step, _KIND_HEADER, self._own_header.tobytes())

    def gather_headers(self, step: int, n_fields: int) -> np.ndarray:
        """(world, n_fields) int64 — every process's step header (own
        row included, like the allgather it replaces).

        A peer that DIED (EOF/ECONNRESET on its established socket)
        contributes a synthesized all-−2 row: field 0 = −2 is exactly
        the producer-failure shape the consumer already raises on
        everywhere together, so peer death fails the whole pod at this
        slot instead of stranding survivors in later phases. The mesh
        teardown inside the failed recv cascades the detection to every
        survivor within one receive."""
        rows: List[Optional[np.ndarray]] = [None] * self._world
        recv_unix: Dict[int, float] = {}
        for p in range(self._world):
            if p == self._pid:
                continue
            try:
                rows[p] = np.frombuffer(
                    self._mesh.recv(
                        p, self._stream, step, _KIND_HEADER
                    ),
                    dtype=np.int64,
                )
            except PodPeerDeadError as e:
                print(f"WARNING: {e}; converting to the synchronized "
                      "-2 failure shape.", flush=True)
                rows[p] = np.full(n_fields, -2, np.int64)
                continue
            recv_unix[p] = time.time()
        # One instant per peer AFTER the loop — the recv path itself
        # stays untouched. send_unix is when WE posted this step's
        # header, recv_unix when the peer's arrived: the (send, recv)
        # pair this process contributes to the midpoint offset estimate
        # (the peer's mirror-image instant completes the round trip).
        from spark_examples_tpu import obs

        for p, rts in recv_unix.items():
            obs.instant(
                "pod.exchange_ts",
                scope="t",
                me=self._pid,
                peer=p,
                step=step,
                stream=self._stream,
                send_unix=self._last_send_unix,
                recv_unix=rts,
            )
        return np.stack(
            [
                r if r is not None else self._own_header
                for r in rows
            ]
        ).reshape(self._world, n_fields)

    def post_confirm(self, step: int, ok: bool) -> None:
        self._own_confirm = np.int64(0 if ok else -2)
        self._post_all(
            step,
            _KIND_CONFIRM,
            np.array([self._own_confirm], np.int64).tobytes(),
        )

    def gather_confirms(self, step: int) -> np.ndarray:
        """(world,) int64 — 0 ok / −2 payload-construction failure (a
        DEAD peer reads as −2 too: same synchronized fail-everywhere
        raise, see :meth:`gather_headers`)."""
        vals = np.empty(self._world, np.int64)
        for p in range(self._world):
            if p == self._pid:
                vals[p] = self._own_confirm
                continue
            try:
                vals[p] = np.frombuffer(
                    self._mesh.recv(
                        p, self._stream, step, _KIND_CONFIRM
                    ),
                    dtype=np.int64,
                )[0]
            except PodPeerDeadError as e:
                print(f"WARNING: {e}; converting to the synchronized "
                      "-2 failure shape.", flush=True)
                vals[p] = -2
        return vals

    def post_check(self, step: int, digest: int) -> None:
        """Post this process's collective-congruence digest for one
        step (non-negative int64 — see utils/collectivecheck). Only
        call when the gathered headers agreed every live process has
        the check enabled."""
        self._own_check = np.int64(digest)
        self._post_all(
            step,
            _KIND_CHECK,
            np.array([self._own_check], np.int64).tobytes(),
        )

    def gather_checks(self, step: int) -> np.ndarray:
        """(world,) int64 — every process's step digest (own value
        included, like the header/confirm gathers)."""
        vals = np.empty(self._world, np.int64)
        for p in range(self._world):
            if p == self._pid:
                vals[p] = self._own_check
                continue
            vals[p] = np.frombuffer(
                self._mesh.recv(p, self._stream, step, _KIND_CHECK),
                dtype=np.int64,
            )[0]
        return vals

    def post_payload(self, step: int, mat: np.ndarray) -> None:
        self._post_all(
            step, _KIND_PAYLOAD, np.ascontiguousarray(mat).tobytes()
        )

    def get_payload(
        self,
        step: int,
        peer: int,
        shape: Tuple[int, ...],
        dtype: Any = np.int32,
    ) -> np.ndarray:
        raw = np.frombuffer(
            self._mesh.recv(peer, self._stream, step, _KIND_PAYLOAD),
            dtype=dtype,
        )
        return raw.reshape(shape)

    def close(self) -> None:
        """Stream teardown: nothing to reclaim — sockets persist for
        the process lifetime and every frame of a completed stream has
        been consumed (synchronized failures stop all peers at the
        same frame boundary)."""

    def poison(self) -> None:
        """Abandoned-stream teardown: see :meth:`_PodSocketMesh.poison`."""
        self._mesh.poison()


@dataclass
class PodSlot:
    """One agreed protocol step, staged for the consumer."""

    step: int
    route: str  # "scatter" | "dense"
    gathered: Optional[np.ndarray]  # scatter: global carrier matrix
    local: Optional[np.ndarray]  # dense: this process's packed panel
    nnz: int
    variants: int
    windows: int  # local windows coalesced into this step's gang


_DONE = object()


class SlotPipeline:
    """Depth-D staged pipeline between the sync thread and the consumer.

    ``produce()`` returns a :class:`PodSlot`, ``None`` when the stream
    has drained, or raises (protocol failures — already synchronized
    across processes by the exchange). Results stage into a bounded
    queue of ``depth`` slots; the consumer's iterator yields them in
    order and re-raises the producer's exception at its slot position.
    ``depth == 0`` degrades to inline lockstep (no thread): one protocol
    step per consumer pull — the ablation/debug mode.
    """

    def __init__(
        self, produce: Callable[[], Optional[PodSlot]], depth: int
    ) -> None:
        if depth < 0:
            raise ValueError(f"pipeline depth must be >= 0, got {depth}")
        self._produce = produce
        self._depth = depth
        self._q: queue.Queue = queue.Queue(maxsize=max(depth, 1))
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _put(self, item: Any) -> bool:
        # Bounded put that gives up when the consumer abandoned the
        # stream — a blocked q.put with no reader would leak the thread
        # (same discipline as arrays/feed.device_prefetch).
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def _run(self) -> None:
        try:
            while not self._stop.is_set():
                slot = self._produce()
                if slot is None:
                    self._put(_DONE)
                    return
                if not self._put(slot):
                    return
        except BaseException as e:  # noqa: BLE001 — re-raised in consumer
            self._put(e)

    def __iter__(self) -> Iterator[PodSlot]:
        if self._depth == 0:
            while True:
                slot = self._produce()
                if slot is None:
                    return
                yield slot
        self._thread = threading.Thread(
            target=self._run, name="pod-sparse-sync", daemon=True
        )
        self._thread.start()
        try:
            while True:
                item = self._q.get()
                if item is _DONE:
                    return
                if isinstance(item, BaseException):
                    raise item
                yield item
        finally:
            # Consumer abandoned the iterator (close/GeneratorExit or an
            # exception in its loop body): release the sync thread.
            self._stop.set()