"""Mesh + collectives: the distributed substrate.

Replaces the reference's entire Spark runtime surface (shuffle, broadcast,
accumulators, driver collects — SURVEY.md §2.10) with XLA collectives over a
``jax.sharding.Mesh``: GSPMD inserts all-gathers for the 2D-sharded Gramian,
``psum`` over the variant axis replaces ``reduceByKey``, and
``jax.distributed`` over DCN replaces driver⇄executor control.

Axis conventions:

- ``"data"`` — the variant axis (the "long sequence" of genomics, millions
  of variants): blocks are scattered across it and partial Gramians are
  psum-reduced. This is the framework's sequence/context parallelism.
- ``"model"`` — the sample axis: the N×N Gramian and the genotype rows are
  sharded across it when N is large (tensor parallelism; the 100k-sample
  stress config).
"""

from spark_examples_tpu.parallel.mesh import make_mesh, DATA_AXIS, MODEL_AXIS
from spark_examples_tpu.parallel.sharded import (
    SpectralGapWarning,
    addressable_sample_bounds,
    gramian_blockwise_global,
    gramian_variant_parallel,
    gramian_variant_parallel_ring,
    sample_bounds_of_indices,
    sharded_gramian_blockwise,
    sharded_pcoa,
    sparse_sharded_gramian_blockwise,
    topk_eig_randomized,
)
from spark_examples_tpu.parallel.distributed import (
    initialize_from_env,
    is_coordinator,
    allreduce_host_stats,
)

__all__ = [
    "SpectralGapWarning",
    "make_mesh",
    "DATA_AXIS",
    "MODEL_AXIS",
    "addressable_sample_bounds",
    "gramian_blockwise_global",
    "gramian_variant_parallel",
    "gramian_variant_parallel_ring",
    "sample_bounds_of_indices",
    "sharded_gramian_blockwise",
    "sharded_pcoa",
    "sparse_sharded_gramian_blockwise",
    "topk_eig_randomized",
    "initialize_from_env",
    "is_coordinator",
    "allreduce_host_stats",
]
