"""CLI: ``python -m spark_examples_tpu.cli.main <command> [flags]``.

One subcommand per reference entry point (``README.md:51-61`` of the
reference lists the runnable mains), with the GenomicsConf/PcaConf flag
surface, plus fixture tooling so every pipeline runs hermetically now that
the Genomics v1 API is retired.
"""

from __future__ import annotations

import argparse
import sys

from spark_examples_tpu.genomics.fixtures import (
    DEFAULT_VARIANT_SET_ID,
    synthetic_cohort,
    synthetic_reads,
)
from spark_examples_tpu.genomics.sources import JsonlSource
from spark_examples_tpu.utils.config import (
    add_analyze_flags,
    add_pca_flags,
    pca_config_from_args,
)

__all__ = ["main"]


def _add_fixture_flags(p: argparse.ArgumentParser) -> None:
    p.add_argument(
        "--fixture-samples",
        type=int,
        default=None,
        help="Run against an in-memory synthetic cohort of this many samples",
    )
    p.add_argument("--fixture-variants", type=int, default=1000)
    p.add_argument("--fixture-seed", type=int, default=0)
    p.add_argument(
        "--fixture-sparse-calls",
        action="store_true",
        help="Omit hom-ref calls from generated records (~10x faster at "
        "large N x V; identical pipeline results)",
    )
    p.add_argument(
        "--fixture-rare-af",
        type=float,
        default=None,
        help="Cap generated variants' allele frequency near this value "
        "(rare-variant biobank shape, ~98%% zeros at 0.01; group AFs "
        "drawn in [0.5x, 1.5x) so population structure survives); "
        "default keeps the common-variant beta draw",
    )


def _network_source(args):
    """HTTP source with credentials — the Client(auth) construction.

    Resolves the credential once on the driver via get_access_token (the
    Authentication.getAccessToken analog, Client.scala:29-46) and ships it
    on every per-shard request.
    """
    from spark_examples_tpu.genomics.auth import get_access_token
    from spark_examples_tpu.genomics.service import HttpVariantSource
    from spark_examples_tpu.resilience import BreakerSet, RetryPolicy
    from spark_examples_tpu.utils.config import GenomicsConfig

    # The declarative resilience surface (docs/RESILIENCE.md): one
    # policy + breaker config for whichever transport serves the run.
    # Fallback defaults come from the config dataclass (itself derived
    # from the resilience layer) — one source of truth.
    retry_policy = RetryPolicy(
        max_attempts=max(
            1,
            getattr(args, "rpc_retries", GenomicsConfig.rpc_retries),
        ),
        deadline=getattr(args, "rpc_retry_deadline", None),
    )

    def breakers(prefix: str) -> BreakerSet:
        return BreakerSet(
            prefix,
            failure_threshold=getattr(
                args, "breaker_threshold", GenomicsConfig.breaker_threshold
            ),
            cooldown_s=getattr(
                args, "breaker_cooldown", GenomicsConfig.breaker_cooldown
            ),
        )

    if args.api_url.startswith("grpc://"):
        # The HTTP/2 server-streaming transport (the reference's bulk
        # channel technology, VariantsRDD.scala:26,210-211). Mirror/
        # cache and binary-frame tiers ride the shared protocol
        # (genomics/mirror.py, genomics/wire.py), so --cache-dir/
        # --mirror-mode work identically on both transports.
        from spark_examples_tpu.genomics.grpc_transport import (
            GrpcVariantSource,
            grpc_available,
        )

        if not grpc_available():
            raise SystemExit(
                "grpc:// transport needs grpcio (pip install "
                "'spark_examples_tpu[grpc]'); the http:// transport "
                "has no extra dependency"
            )
        idle = getattr(
            args, "grpc_idle_timeout", GenomicsConfig.grpc_idle_timeout
        )
        return GrpcVariantSource(
            args.api_url,
            credentials=get_access_token(args.client_secrets),
            idle_timeout=idle if idle else None,
            retry_policy=retry_policy,
            breakers=breakers(f"grpc:{args.api_url}:"),
            cache_dir=getattr(args, "cache_dir", None),
            mirror_mode=getattr(args, "mirror_mode", "full"),
            cold_stream=getattr(args, "cold_stream", True),
        )
    return HttpVariantSource(
        args.api_url,
        credentials=get_access_token(args.client_secrets),
        cache_dir=getattr(args, "cache_dir", None),
        mirror_mode=getattr(args, "mirror_mode", "full"),
        retry_policy=retry_policy,
        breakers=breakers(f"http:{args.api_url}:"),
        cold_stream=getattr(args, "cold_stream", True),
    )


def _offline_source(args, references: str):
    """JSONL-dir or synthetic-fixture source, or None if neither flagged."""
    if args.input_path:
        return JsonlSource(args.input_path)
    if args.fixture_samples:
        if getattr(args, "all_references", False):
            # Cover exactly what the --all-references manifest queries.
            from spark_examples_tpu.genomics.shards import (
                references_for_all,
            )

            references = references_for_all()
        return synthetic_cohort(
            args.fixture_samples,
            args.fixture_variants,
            references=references,
            seed=args.fixture_seed,
            sparse_calls=args.fixture_sparse_calls,
            rare_variant_af=getattr(args, "fixture_rare_af", None),
            variant_set_id=(args.variant_set_ids or [DEFAULT_VARIANT_SET_ID])[0],
        )
    return None


def _resolve_source(args, references: str):
    # Offline sources (fixture/JSONL) never consume credentials;
    # --client-secrets applies to the network source only.
    if args.api_url:
        return _network_source(args)
    source = _offline_source(args, references)
    if source is None:
        raise SystemExit(
            "No data source: pass --api-url <service>, --input-path "
            "<jsonl cohort dir>, or --fixture-samples N (the Genomics v1 "
            "API is retired; serve-cohort hosts a compatible service)"
        )
    return source


def _cmd_pca(args) -> int:
    _enable_compile_cache()
    from spark_examples_tpu.models.pca import VariantsPcaDriver
    from spark_examples_tpu.parallel.distributed import initialize_from_env

    initialize_from_env()  # no-op without cluster env vars
    conf = pca_config_from_args(args)
    if not args.variant_set_ids:
        conf.variant_set_ids = [DEFAULT_VARIANT_SET_ID]
    refs = conf.references
    source = _resolve_source(args, refs)
    mesh = None
    if conf.mesh_shape:
        from spark_examples_tpu.parallel.mesh import make_mesh

        mesh = make_mesh(conf.mesh_shape)
    driver = VariantsPcaDriver(conf, source, mesh=mesh)
    driver.run()
    return 0


def _cmd_generate_fixture(args) -> int:
    """Write a JSONL cohort directory for offline runs."""
    src = synthetic_cohort(
        args.fixture_samples or 100,
        args.fixture_variants,
        references=args.references,
        seed=args.fixture_seed,
        sparse_calls=args.fixture_sparse_calls,
        rare_variant_af=getattr(args, "fixture_rare_af", None),
        variant_set_id=(args.variant_set_ids or [DEFAULT_VARIANT_SET_ID])[0],
    )
    if args.fixture_tumor_normal:
        # Tumor/normal pair for reads-example 4.
        from spark_examples_tpu.genomics.fixtures import synthetic_tumor_normal

        pair = synthetic_tumor_normal(
            args.fixture_tumor_normal,
            references=args.reads_references or "1:100000000:100002000",
            seed=args.fixture_seed,
        )
        src.add_reads(pair.reads_records())
    elif args.fixture_reads:
        # Same directory serves reads examples 1-3 via --input-path; note
        # the region must cover the example's query window
        # (--reads-references defaults to --references).
        reads_src = synthetic_reads(
            args.fixture_reads,
            references=args.reads_references or args.references,
            seed=args.fixture_seed,
        )
        src.add_reads(reads_src.reads_records())
    src.dump(args.out)
    print(f"Wrote cohort to {args.out}")
    return 0


def _resolve_reads_source(args, references: str):
    """Returns (source, read_group_set_id)."""
    from spark_examples_tpu.genomics.fixtures import FIXTURE_READSET_ID

    if args.api_url:
        return _network_source(args), (args.read_group_set_id or "")
    if args.input_path:
        # Local cohorts default to no readset filter (serve whatever the
        # directory holds); --read-group-set-id narrows it.
        return JsonlSource(args.input_path), (args.read_group_set_id or "")
    if args.fixture_reads:
        return (
            synthetic_reads(
                args.fixture_reads,
                references=references,
                seed=args.fixture_seed,
            ),
            FIXTURE_READSET_ID,
        )
    raise SystemExit(
        "No reads source: pass --input-path <jsonl cohort dir> or "
        "--fixture-reads N"
    )


def _cmd_search_variants(args, fn) -> int:
    conf = pca_config_from_args(args)
    if not args.variant_set_ids:
        conf.variant_set_ids = [DEFAULT_VARIANT_SET_ID]
    source = _resolve_source(args, args.references)
    fn(
        source,
        variant_set_id=conf.variant_set_ids[0],
        references=args.references,
        bases_per_shard=conf.bases_per_partition,
    )
    return 0


def _cmd_reads_example(args) -> int:
    _enable_compile_cache()
    from spark_examples_tpu.models import search_reads as sr

    n = args.example
    if n == 1:
        refs = args.references or (
            f"11:{sr.Examples.CILANTRO - 1000}:{sr.Examples.CILANTRO + 1000}"
        )
        source, rgsid = _resolve_reads_source(args, refs)
        for line in sr.pileup(
            source,
            rgsid,
            references=refs,
            bases_per_shard=args.bases_per_partition,
        ):
            print(line)
    elif n == 2:
        refs = args.references  # None → whole chr21, reference behavior
        source, rgsid = _resolve_reads_source(args, refs or "21:1:48129895")
        sr.average_coverage(
            source,
            rgsid,
            references=refs,
            bases_per_shard=args.bases_per_partition,
        )
    elif n == 3:
        refs = args.references
        source, rgsid = _resolve_reads_source(args, refs or "21:1:48129895")
        out = sr.per_base_depth_example(
            source,
            rgsid,
            references=refs,
            out_path=args.output_path or ".",
            bases_per_shard=args.bases_per_partition,
        )
        print(f"Wrote {out}")
    elif n == 4:
        from spark_examples_tpu.genomics.fixtures import (
            NORMAL_READSET_ID,
            TUMOR_READSET_ID,
            synthetic_tumor_normal,
        )

        refs = args.references or "1:100000000:101000000"
        if args.api_url:
            source = _network_source(args)
            normal_id = args.normal_id or NORMAL_READSET_ID
            tumor_id = args.tumor_id or TUMOR_READSET_ID
        elif args.input_path:
            source = JsonlSource(args.input_path)
            # Local cohorts default to the fixture pair ids (the DREAM API
            # ids remain available via the flags).
            normal_id = args.normal_id or NORMAL_READSET_ID
            tumor_id = args.tumor_id or TUMOR_READSET_ID
        elif args.fixture_reads:
            source = synthetic_tumor_normal(
                args.fixture_reads, references=refs, seed=args.fixture_seed
            )
            normal_id, tumor_id = NORMAL_READSET_ID, TUMOR_READSET_ID
        else:
            raise SystemExit(
                "No reads source: pass --api-url, --input-path, or "
                "--fixture-reads N"
            )
        out = sr.tumor_normal_diff(
            source,
            normal_id=normal_id,
            tumor_id=tumor_id,
            references=refs,
            out_path=args.output_path or ".",
            bases_per_shard=args.bases_per_partition,
        )
        print(f"Wrote {out}")
    else:
        raise SystemExit(f"unknown reads example {n}")
    stats = getattr(source, "stats", None)
    if stats is not None and stats.reads_read == 0:
        print(
            "WARNING: no reads matched the queried region/readset — check "
            "that the cohort covers the example's region (--references) "
            "and readset id (--read-group-set-id / --normal-id/--tumor-id)",
            file=sys.stderr,
        )
    return 0


def _cmd_pairhmm(args) -> int:
    """The reads-side kernel pipeline: batched PairHMM scoring."""
    _enable_compile_cache()
    from spark_examples_tpu.models.pairhmm import PairHmmDriver

    conf = pca_config_from_args(args)
    # Default region = synthetic_reads' default window, so a bare
    # `pairhmm --fixture-reads N` scores out of the box (the same
    # default-region discipline as the reads examples).
    conf.references = args.references or "11:6888648:6890648"
    source, rgsid = _resolve_reads_source(args, conf.references)
    if not conf.read_group_set_id:
        conf.read_group_set_id = rgsid
    driver = PairHmmDriver(conf, source)
    driver.run(out_path=args.output_path)
    return 0


def _cmd_pca_bridge(args) -> int:
    """Serve the PcaBackend seam over TCP."""
    _enable_compile_cache()
    from spark_examples_tpu.bridge import PcaBridgeServer, TpuPcaBackend

    mesh = None
    if args.mesh_shape:
        from spark_examples_tpu.parallel.mesh import make_mesh

        mesh = make_mesh(args.mesh_shape)
    server = PcaBridgeServer(
        TpuPcaBackend(mesh=mesh, block_variants=args.block_variants),
        port=args.port,
    ).start()
    print(f"PcaBackend bridge listening on 127.0.0.1:{server.port}")
    try:
        import threading

        threading.Event().wait()
    except KeyboardInterrupt:
        server.stop()
    return 0


def _analysis_tier(args, source):
    """The --analyze job tier: re-entrant PCA engine over the served
    source + bounded admission + crash-safe journal (serving/)."""
    from spark_examples_tpu.serving import (
        DEFAULT_HEARTBEAT_S,
        DEFAULT_LEASE_TTL_S,
        AnalysisEngine,
        AnalysisJobTier,
        LeaseManager,
    )

    _REPLICA_FLAG_DEFAULTS = {
        "--replica-id": None,
        "--replica-lease-ttl": DEFAULT_LEASE_TTL_S,
        "--replica-heartbeat": DEFAULT_HEARTBEAT_S,
    }

    # Loud validation before any work, like every other flag surface
    # (--prefetch-depth/--ingest-workers discipline): a zero-worker
    # tier would accept jobs and never run them.
    for flag, value in (
        ("--analyze-workers", args.analyze_workers),
        ("--analyze-queue-depth", args.analyze_queue_depth),
        ("--analyze-tenant-quota", args.analyze_tenant_quota),
        ("--analyze-cache-size", args.analyze_cache_size),
    ):
        if value < 1:
            raise SystemExit(f"{flag} must be >= 1, got {value}")
    for flag, value in (
        ("--delta-max-samples", args.delta_max_samples),
        ("--gang-max-samples", args.gang_max_samples),
    ):
        if value < 0:
            raise SystemExit(
                f"{flag} must be >= 0 (0 disables), got {value}"
            )
    if args.store_dir is None:
        # --replica-* only mean something over a shared store; a
        # silently ignored flag is how operators think they deployed
        # failover and discover otherwise during an outage.
        for flag, value in (
            ("--replica-id", args.replica_id),
            ("--replica-lease-ttl", args.replica_lease_ttl),
            ("--replica-heartbeat", args.replica_heartbeat),
        ):
            if value is not None and value != _REPLICA_FLAG_DEFAULTS[flag]:
                raise SystemExit(
                    f"{flag} requires --store-dir (replicated serving "
                    "needs a shared durable store)"
                )
    else:
        if args.replica_lease_ttl <= 0:
            raise SystemExit(
                "--replica-lease-ttl must be > 0, got "
                f"{args.replica_lease_ttl}"
            )
        if not 0 < args.replica_heartbeat < args.replica_lease_ttl:
            raise SystemExit(
                "--replica-heartbeat must satisfy 0 < heartbeat < "
                f"lease ttl ({args.replica_lease_ttl}), got "
                f"{args.replica_heartbeat}"
            )
    # Jobs jit-compile on demand; the persistent cache means job #1
    # after a restart pays no recompile either.
    _enable_compile_cache()
    mesh = None
    if args.mesh_shape:
        from spark_examples_tpu.parallel.mesh import make_mesh

        mesh = make_mesh(args.mesh_shape)
    base = pca_config_from_args(args)
    if not args.variant_set_ids:
        base.variant_set_ids = [DEFAULT_VARIANT_SET_ID]
    if not args.analyze_journal_dir and not args.store_dir:
        print(
            "WARNING: --analyze without --analyze-journal-dir: jobs are "
            "in-memory only and a crash forgets them all.",
            file=sys.stderr,
        )
    import os

    replica = None
    delta_fence = None
    if args.store_dir:
        from spark_examples_tpu.store import LocalDirStore

        replica = LeaseManager(
            LocalDirStore(args.store_dir),
            replica_id=args.replica_id,
            ttl_s=args.replica_lease_ttl,
            heartbeat_s=args.replica_heartbeat,
        )
        if not replica.start():
            # Degraded from birth (store unreachable): the tier still
            # comes up — single-replica local mode, journal/ckpt on
            # local disk, serving_store_degraded=1. Restart with a
            # reachable store to rejoin the replica set.
            print(
                "WARNING: --store-dir unreachable at startup; serving "
                "single-replica local (restart with a reachable store "
                "to rejoin the replica set).",
                file=sys.stderr,
            )
        delta_fence = replica.check_fence
    # The delta cache persists beside the journal — or, replicated, in
    # the shared store so a warm delta computed on one replica answers
    # on all: a kill -9'd server restarted on the same directory
    # answers ±k cohort deltas warm (checksummed write-through; torn
    # entries drop loudly to cold on re-load).
    if args.store_dir and args.delta_max_samples > 0:
        delta_persist = os.path.join(args.store_dir, "deltas")
    elif args.analyze_journal_dir and args.delta_max_samples > 0:
        delta_persist = os.path.join(args.analyze_journal_dir, "deltas")
    else:
        delta_persist = None
    tier = AnalysisJobTier(
        AnalysisEngine(
            source,
            mesh=mesh,
            delta_max_samples=args.delta_max_samples,
            delta_persist_dir=delta_persist,
            delta_fence=delta_fence,
        ),
        base,
        queue_depth=args.analyze_queue_depth,
        tenant_quota=args.analyze_tenant_quota,
        workers=args.analyze_workers,
        journal_dir=args.analyze_journal_dir,
        cache_size=args.analyze_cache_size,
        gang_max_samples=args.gang_max_samples,
        replica=replica,
    )
    return tier.start()


def _cmd_serve_cohort(args) -> int:
    """Host a cohort as a Genomics-compatible HTTP service."""
    from spark_examples_tpu.genomics.service import GenomicsServiceServer

    source = _offline_source(args, args.references)
    if source is None:
        raise SystemExit(
            "serve-cohort needs --input-path <jsonl dir> or "
            "--fixture-samples N"
        )
    warm = getattr(source, "ensure_serving_index", None)
    if warm is not None:
        # Index BEFORE accepting requests: at all-autosomes scale a lazy
        # build on the first shard request outlives client socket
        # timeouts (measured round 5: >60 s behind the first GET).
        print("Indexing cohort for serving ...", flush=True)
        print(f"Indexed {warm()} variant records.", flush=True)
    grpc_server = None
    if args.grpc_port is not None:
        from spark_examples_tpu.genomics.grpc_transport import (
            GrpcGenomicsServer,
            grpc_available,
        )

        if not grpc_available():
            raise SystemExit(
                "--grpc-port needs grpcio (pip install "
                "'spark_examples_tpu[grpc]'); omit it to serve HTTP only"
            )
        from spark_examples_tpu.bridge.backend import TpuPcaBackend

        # The gRPC endpoint also exposes the ComputePca dense-math seam
        # (SURVEY §7.6's "small gRPC service"): external drivers stream
        # call lists and get coordinates back from THIS host's
        # accelerator — so the endpoint honors the same mesh/block flags
        # and compile cache pca-bridge does. TpuPcaBackend imports jax
        # lazily; the cache env setup is env-only, so serving stays
        # host-only until a ComputePca call actually arrives.
        _enable_compile_cache()
        mesh = None
        if args.mesh_shape:
            from spark_examples_tpu.parallel.mesh import make_mesh

            mesh = make_mesh(args.mesh_shape)
        grpc_server = GrpcGenomicsServer(
            source,
            port=args.grpc_port,
            token=args.token,
            host=args.host,
            pca_backend=TpuPcaBackend(
                mesh=mesh, block_variants=args.block_variants
            ),
        ).start()
        print(
            f"gRPC stream service on grpc://{args.host}:{grpc_server.port}"
            + (" (token auth)" if args.token else ""),
            flush=True,
        )
    import contextlib
    import os

    job_tier = None
    stack = contextlib.ExitStack()
    try:
        if args.analyze:
            # The live introspection plane (/metrics, /statusz,
            # /jobs?trace=1) reads the ambient registry and tracer, so
            # an analysis server keeps one collection session open for
            # its whole lifetime — unless the CLI entrypoint already
            # opened one for --trace-out/--metrics-out artifacts.
            from spark_examples_tpu.obs.session import TelemetrySession
            from spark_examples_tpu.obs.tracer import collection_active

            if not collection_active():
                stack.enter_context(
                    TelemetrySession(command="serve-cohort")
                )
            if args.analyze_journal_dir:
                # Crash flight recorder rides beside the journal: the
                # last K span/metric transitions land in
                # <journal>/flightrec/ on watchdog exit-77, SIGTERM,
                # or an unhandled exception.
                from spark_examples_tpu.obs import flightrec

                flightrec.install(
                    os.path.join(
                        args.analyze_journal_dir, "flightrec"
                    )
                )
            job_tier = _analysis_tier(args, source)
            print(
                f"Analysis tier up: queue depth "
                f"{args.analyze_queue_depth}, tenant quota "
                f"{args.analyze_tenant_quota}, "
                f"{args.analyze_workers} worker(s)"
                + (
                    f", journal {args.analyze_journal_dir}"
                    if args.analyze_journal_dir
                    else " (no journal)"
                )
                + (
                    f", deltas <= {args.delta_max_samples} samples"
                    if args.delta_max_samples > 0
                    else ", deltas off"
                )
                + (
                    f", gangs <= {args.gang_max_samples} samples"
                    if args.gang_max_samples > 0
                    else ", gangs off"
                )
                + (
                    ", replica "
                    f"{job_tier.replica_health()['replica_id']} on "
                    f"store {args.store_dir} (lease ttl "
                    f"{args.replica_lease_ttl:g}s)"
                    if args.store_dir
                    else ""
                ),
                flush=True,
            )
        server = GenomicsServiceServer(
            source,
            port=args.port,
            token=args.token,
            host=args.host,
            job_tier=job_tier,
        )
        print(
            f"Genomics service listening on http://{args.host}:{server.port}"
            + (" (token auth)" if args.token else ""),
            flush=True,
        )
        try:
            server.serve_forever()
        except KeyboardInterrupt:
            server.stop()
    finally:
        # Covers HTTP bind failures too — a started gRPC server or job
        # tier must never outlive the command that printed its URL.
        if grpc_server is not None:
            grpc_server.stop()
        if job_tier is not None:
            job_tier.close()
        stack.close()
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="spark_examples_tpu")
    sub = p.add_subparsers(dest="command", required=True)

    pca = sub.add_parser("pca", help="VariantsPcaDriver: PCoA over a cohort")
    add_pca_flags(pca)
    _add_fixture_flags(pca)
    pca.set_defaults(fn=_cmd_pca)

    gen = sub.add_parser(
        "generate-fixture", help="Write a synthetic JSONL cohort"
    )
    add_pca_flags(gen)
    _add_fixture_flags(gen)
    gen.add_argument("--out", required=True)
    gen.add_argument(
        "--fixture-reads",
        type=int,
        default=None,
        help="Also write reads.jsonl with this many synthetic reads",
    )
    gen.add_argument(
        "--reads-references",
        default=None,
        help="Region for generated reads (defaults to --references)",
    )
    gen.add_argument(
        "--fixture-tumor-normal",
        type=int,
        default=None,
        help="Write a tumor/normal reads pair (for reads-example 4) "
        "instead of a single readset",
    )
    gen.set_defaults(fn=_cmd_generate_fixture)

    from spark_examples_tpu.models.search_variants import (
        search_variants_brca1,
        search_variants_klotho,
    )

    from spark_examples_tpu.genomics.shards import (
        BRCA1_REFERENCES,
        KLOTHO_REFERENCES,
    )

    for name, fn, refs in (
        ("search-variants-klotho", search_variants_klotho, KLOTHO_REFERENCES),
        ("search-variants-brca1", search_variants_brca1, BRCA1_REFERENCES),
    ):
        sv = sub.add_parser(name, help=f"{name} example driver")
        add_pca_flags(sv)
        _add_fixture_flags(sv)
        sv.set_defaults(references=refs)
        sv.set_defaults(fn=lambda a, _f=fn: _cmd_search_variants(a, _f))

    reads = sub.add_parser(
        "reads-example", help="SearchReadsExample 1-4 drivers"
    )
    add_pca_flags(reads)
    _add_fixture_flags(reads)
    reads.add_argument("--example", type=int, required=True, choices=[1, 2, 3, 4])
    reads.add_argument(
        "--fixture-reads",
        type=int,
        default=None,
        help="Run against synthetic reads",
    )
    reads.add_argument("--normal-id", default=None)
    reads.add_argument("--tumor-id", default=None)
    reads.set_defaults(references=None, fn=_cmd_reads_example)

    phmm = sub.add_parser(
        "pairhmm",
        help="Score every read against its consensus haplotype with "
        "the batched TPU PairHMM forward kernel",
    )
    add_pca_flags(phmm)
    _add_fixture_flags(phmm)
    phmm.add_argument(
        "--fixture-reads",
        type=int,
        default=None,
        help="Run against synthetic reads",
    )
    phmm.set_defaults(references=None, fn=_cmd_pairhmm)

    bridge = sub.add_parser(
        "pca-bridge", help="Serve the PcaBackend seam over TCP"
    )
    add_pca_flags(bridge)
    bridge.add_argument("--port", type=int, default=18717)
    bridge.set_defaults(fn=_cmd_pca_bridge)

    serve = sub.add_parser(
        "serve-cohort",
        help="Host a cohort as a Genomics-compatible HTTP service",
    )
    add_pca_flags(serve)
    add_analyze_flags(serve)
    _add_fixture_flags(serve)
    serve.add_argument("--port", type=int, default=18718)
    serve.add_argument(
        "--grpc-port",
        type=int,
        default=None,
        help="Also serve the gRPC/HTTP-2 server-streaming transport on "
        "this port (0 = auto-pick; clients connect with --api-url "
        "grpc://host:port). The HTTP service keeps the mirror/cache "
        "endpoints; both front the same cohort",
    )
    serve.add_argument("--host", default="127.0.0.1")
    serve.add_argument(
        "--token",
        default=None,
        help="Require this bearer token on every request",
    )
    serve.set_defaults(fn=_cmd_serve_cohort)

    return p


def _enable_compile_cache() -> None:
    """Persistent XLA compile cache for the jit-compiling subcommands.

    The first ``eigh`` compile at N≈2500 is minutes through a
    remote-compile tunnel; without a persistent cache every CLI process
    pays it again (measured: the warm all-autosomes run spent 145.6 s of
    its 260.8 s total re-compiling programs the previous run had already
    built). Called lazily from the handlers that actually compile (pca,
    reads-example, pca-bridge, and serve-cohort WITH --grpc-port — its
    ComputePca seam jit-compiles on demand) so host-only subcommands
    (generate-fixture, plain serve-cohort, search-variants) never import
    jax or touch the filesystem for it. Default location: the user cache dir
    (``$XDG_CACHE_HOME``/``~/.cache``); the source checkout's
    ``.jax_cache/`` is used only when the checkout is writable AND already
    has one (an opt-in anchor — dev trees keep their warm cache, but a
    read-only or pristine install never grows a side-effect directory).
    ``SPARK_EXAMPLES_TPU_COMPILE_CACHE=<path>`` overrides; ``=0``
    disables. The dir is host-feature-keyed (utils/compile_cache.py), so
    a cache populated on another host can't feed this one illegal code.
    """
    import os

    from spark_examples_tpu.utils.compile_cache import (
        enable_persistent_cache,
    )

    override = os.environ.get("SPARK_EXAMPLES_TPU_COMPILE_CACHE", "")
    if override == "0":
        return
    if override:
        enable_persistent_cache(override)
        return
    pkg_root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    anchored = os.path.join(pkg_root, ".jax_cache")
    if os.path.isdir(anchored) and os.access(anchored, os.W_OK):
        enable_persistent_cache(anchored)
        return
    base = os.environ.get("XDG_CACHE_HOME") or os.path.join(
        os.path.expanduser("~"), ".cache"
    )
    enable_persistent_cache(os.path.join(base, "spark_examples_tpu"))


def main(argv=None) -> int:
    import contextlib

    from spark_examples_tpu.resilience import faults

    args = build_parser().parse_args(argv)
    # Deterministic fault plane: --fault-plan wins over the
    # SPARK_EXAMPLES_TPU_FAULT_PLAN env var; either scopes the plan to
    # this one command (chaos soaks drive the CLI exactly like a real
    # run — docs/RESILIENCE.md).
    spec = getattr(args, "fault_plan", None)
    plan = (
        faults.FaultPlan.from_spec(spec) if spec else faults.plan_from_env()
    )
    with faults.active_plan(plan) if plan else contextlib.nullcontext():
        outs = {
            name: getattr(args, name, None)
            for name in ("trace_out", "metrics_out", "manifest_out")
        }
        if not any(outs.values()):
            return args.fn(args)
        # One telemetry session per CLI run: spans/metrics collected by
        # the ambient helpers everywhere below, artifacts written on
        # exit — on the failure path too, so a crashed run leaves its
        # timeline behind. (build_manifest drops non-JSON-serializable
        # config values itself.)
        from spark_examples_tpu.obs import telemetry_session

        config = {
            k: v for k, v in sorted(vars(args).items()) if k != "fn"
        }
        with telemetry_session(
            command=args.command, config=config, **outs
        ):
            return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
