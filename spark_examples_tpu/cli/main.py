"""CLI: ``python -m spark_examples_tpu.cli.main <command> [flags]``.

One subcommand per reference entry point (``README.md:51-61`` of the
reference lists the runnable mains), with the GenomicsConf/PcaConf flag
surface, plus fixture tooling so every pipeline runs hermetically now that
the Genomics v1 API is retired.
"""

from __future__ import annotations

import argparse
import sys

from spark_examples_tpu.genomics.fixtures import (
    DEFAULT_VARIANT_SET_ID,
    synthetic_cohort,
)
from spark_examples_tpu.genomics.sources import JsonlSource
from spark_examples_tpu.utils.config import (
    add_pca_flags,
    pca_config_from_args,
)

__all__ = ["main"]


def _add_fixture_flags(p: argparse.ArgumentParser) -> None:
    p.add_argument(
        "--fixture-samples",
        type=int,
        default=None,
        help="Run against an in-memory synthetic cohort of this many samples",
    )
    p.add_argument("--fixture-variants", type=int, default=1000)
    p.add_argument("--fixture-seed", type=int, default=0)


def _resolve_source(args, references: str):
    if args.input_path:
        return JsonlSource(args.input_path)
    if args.fixture_samples:
        return synthetic_cohort(
            args.fixture_samples,
            args.fixture_variants,
            references=references,
            seed=args.fixture_seed,
            variant_set_id=(args.variant_set_ids or [DEFAULT_VARIANT_SET_ID])[0],
        )
    raise SystemExit(
        "No data source: pass --input-path <jsonl cohort dir> or "
        "--fixture-samples N (the Genomics v1 API is retired; network "
        "sources implement the VariantSource protocol)"
    )


def _cmd_pca(args) -> int:
    from spark_examples_tpu.models.pca import VariantsPcaDriver

    conf = pca_config_from_args(args)
    if not args.variant_set_ids:
        conf.variant_set_ids = [DEFAULT_VARIANT_SET_ID]
    refs = conf.references
    source = _resolve_source(args, refs)
    mesh = None
    if conf.mesh_shape:
        from spark_examples_tpu.parallel.mesh import make_mesh

        mesh = make_mesh(conf.mesh_shape)
    driver = VariantsPcaDriver(conf, source, mesh=mesh)
    driver.run()
    return 0


def _cmd_generate_fixture(args) -> int:
    """Write a JSONL cohort directory for offline runs."""
    src = synthetic_cohort(
        args.fixture_samples or 100,
        args.fixture_variants,
        references=args.references,
        seed=args.fixture_seed,
        variant_set_id=(args.variant_set_ids or [DEFAULT_VARIANT_SET_ID])[0],
    )
    src.dump(args.out)
    print(f"Wrote cohort to {args.out}")
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="spark_examples_tpu")
    sub = p.add_subparsers(dest="command", required=True)

    pca = sub.add_parser("pca", help="VariantsPcaDriver: PCoA over a cohort")
    add_pca_flags(pca)
    _add_fixture_flags(pca)
    pca.set_defaults(fn=_cmd_pca)

    gen = sub.add_parser(
        "generate-fixture", help="Write a synthetic JSONL cohort"
    )
    add_pca_flags(gen)
    _add_fixture_flags(gen)
    gen.add_argument("--out", required=True)
    gen.set_defaults(fn=_cmd_generate_fixture)

    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
