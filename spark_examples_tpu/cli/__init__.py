"""Command-line entry points — one per reference example driver."""
