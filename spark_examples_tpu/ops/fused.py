"""Fused PCoA: streamed packed accumulation + a single-dispatch finish.

Why this exists (round-4/5 roofline work): through the axon relay the PCoA
phase is **link-bound** — the measured host→device path moves ~48 MB/s and
every synchronous host-visible result costs a ~65 ms roundtrip, while the
device-side compute for the whole bench workload (Gramian + centering +
top-k eig at N=2504, V=65536) is ~10 ms. The fastest shape the computation
can take is therefore:

    bit-packed transfer        (the irreducible bytes, 8× fewer than int8)
    overlapped with host pack  (np.packbits runs in the prefetch thread
                               while the previous chunk is in flight)
    async accumulate dispatches (G += unpack(chunk) @ unpack(chunk).T,
                               donated in place in HBM — enqueue is
                               non-blocking, so dispatches hide entirely
                               under the transfer stream)
    ONE finish dispatch        (center → CholeskyQR subspace eig → row
                               sums, all on device)
    ONE packed readback        (coords, eigenvalues, row sums in a single
                               (N, p+3) f32 array — one sync roundtrip,
                               not three)

Round 4 shipped a one-put-one-dispatch variant of this; it serialized the
host-side pack (~0.15 s) and the full 20.5 MB put ahead of the dispatch and
landed at 0.775 of the link roofline. This version streams chunks through
:func:`spark_examples_tpu.arrays.feed.device_prefetch` — the same
double-buffered feed the blockwise product path uses — so pack and
transfer overlap and the only serial terms left are the link itself and
one sync floor. It is also the SHIPPED path: ``VariantsPcaDriver`` routes
single-host unsharded runs through :func:`fused_finish` (``--pca-mode``),
and ``bench.py``'s ``fused`` mode calls :func:`pcoa_fused_blocks`, the
exact composition the CLI executes.

The top-k eigendecomposition inside the finish program is randomized
subspace iteration with **CholeskyQR** panel orthonormalization: ``qr`` on
TPU lowers to sequential Householder steps (measured 2.4× slower
end-to-end), whereas CholeskyQR is two MXU matmuls plus a (p, p) Cholesky
+ triangular solve — numerically fine here because panels are
re-orthonormalized every iteration and PCoA spectra are mild (κ(panel
Gram) ≈ (λ₁/λ_p)² per sweep; the f32 limit ~2^12 dwarfs realistic
population-structure ratios). Convergence is *checked*, not assumed: the
finish program computes the top-k Ritz residuals ``‖C·v − λ·v‖/|λ|`` from
its own final matmul and :func:`fused_finish` raises them as a loud
:class:`EigResidualWarning` when they exceed the parity bar's scale.

Semantics match :func:`spark_examples_tpu.ops.pcoa.pcoa` exactly: raw
sign-normalized eigenvectors of the double-centered Gramian ordered by
|λ| descending (the MLlib composition equivalence — pcoa.py module
docstring; reference ``VariantsPca.scala:198-231``). Accuracy vs dense
``eigh`` is set by ``iters``; the defaults land ≤1e-4 max coordinate error
on structured (population-structure) cohorts and are verified against the
f64 MLlib-literal golden in tests and in ``bench.py``. The spectral-gap
degeneracy check runs host-side on the returned Ritz values, exactly as
the dense path's (:func:`~spark_examples_tpu.ops.pcoa.check_spectral_gap`).
"""

from __future__ import annotations

import warnings
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from spark_examples_tpu.ops.centering import double_center
from spark_examples_tpu.ops.gramian import gramian_blockwise
from spark_examples_tpu.ops.pcoa import (
    check_spectral_gap,
    normalize_eigvec_signs,
)

__all__ = [
    "EigResidualWarning",
    "fused_finish",
    "fused_forward",
    "pcoa_fused_blocks",
    "pcoa_fused_packed",
    "subspace_eig_cholqr",
]

# The shipped sweep defaults — shared by fused_finish and fused_forward
# so the driver contract (__graft_entry__) certifies exactly the
# composition --pca-mode auto runs; changing one changes both.
_DEF_OVERSAMPLE = 8
_DEF_ITERS = 40


class EigResidualWarning(UserWarning):
    """Subspace iteration left a top-k Ritz residual above the bar."""


def subspace_eig_cholqr(c, k: int, oversample: int = 8, iters: int = 16,
                        key=None):
    """Top-|λ| eigenpairs of symmetric ``c`` — jittable, MXU-only inner loop.

    Returns ``(vecs (N, p), vals (p,), resid ())`` with ``p = k+oversample``,
    |λ|-ordered and sign-normalized; ``resid`` is the max top-k relative
    Ritz residual ``‖C·v − λ·v‖/|λ|`` computed from the final products
    (no extra O(N²) work). Callers slice to k after the host-side checks.
    """
    n = c.shape[0]
    p = min(n, k + oversample)
    if key is None:
        key = jax.random.PRNGKey(0)
    q = jax.random.normal(key, (n, p), c.dtype)
    eye = jnp.eye(p, dtype=c.dtype)

    # TPU matmuls default to bf16 MXU passes — fine for the int8-exact
    # Gramian, fatal for eigenvector refinement (the iteration stalls at
    # ~1e-4 instead of converging to ~3e-7, measured on chip round 4).
    # Panel matmuls are O(N²p) — forcing f32-equivalent precision costs
    # ~3× on a term that is ~1% of the phase.
    with jax.default_matmul_precision("float32"):

        def body(q, _):
            y = c @ q
            # CholeskyQR: orthonormalize through the (p, p) Gram factor.
            # The jitter is SCALE-RELATIVE (eps · mean column norm², per
            # advisor round 4: an absolute finfo.tiny only guards
            # exactly-zero columns) plus a tiny absolute floor for the
            # all-zero-C edge; near-rank-deficient panels stay
            # factorizable and the discarded directions are dropped by
            # the |λ| ordering anyway.
            yty = y.T @ y
            jitter = (
                jnp.finfo(c.dtype).eps * (jnp.trace(yty) / p)
                + jnp.finfo(c.dtype).tiny
            )
            r = jnp.linalg.cholesky(yty + jitter * eye)
            q = jax.lax.linalg.triangular_solve(
                r, y, left_side=False, lower=True, transpose_a=True
            )
            return q, None

        q, _ = jax.lax.scan(body, q, None, length=iters)
        y = c @ q
        b = q.T @ y
        w, u = jnp.linalg.eigh(b)
        order = jnp.argsort(-jnp.abs(w))
        vecs = q @ u[:, order]
        vals = w[order]
        # Top-k Ritz residuals from the products already in hand:
        # C·v = (C·q)·u = y·u, so ‖C·v − λ·v‖ needs no new O(N²) matmul.
        uk, wk = u[:, order[:k]], vals[:k]
        rk = y @ uk - (q @ uk) * wk
        resid = jnp.max(
            jnp.linalg.norm(rk, axis=0)
            / jnp.maximum(jnp.abs(wk), jnp.finfo(c.dtype).tiny)
        )
        return normalize_eigvec_signs(vecs), vals, resid


@partial(jax.jit, static_argnames=("k", "oversample", "iters"))
def _finish_jit(g, k, oversample, iters, key):
    """Center → subspace eig → row sums, packed into ONE output array.

    The packing matters through a latency-bound link: three separate
    device→host reads would pay three ~65 ms sync roundtrips; one
    (N, p+3) f32 array pays one. Layout: ``[:, :p]`` eigenvectors,
    ``[:, p]`` row sums of G (the "Non zero rows" parity print,
    ``VariantsPca.scala:207-208``), ``[:p, p+1]`` eigenvalues,
    ``[0, p+2]`` max top-k relative Ritz residual. ``n ≥ p`` always
    (``p = min(n, k+oversample)``), so the value rows exist.
    """
    gf = g.astype(jnp.float32)
    row_sums = jnp.sum(gf, axis=1)
    c = double_center(gf)
    vecs, vals, resid = subspace_eig_cholqr(
        c, k, oversample=oversample, iters=iters, key=key
    )
    n, p = vecs.shape
    out = jnp.zeros((n, p + 3), jnp.float32)
    out = out.at[:, :p].set(vecs)
    out = out.at[:, p].set(row_sums)
    out = out.at[:p, p + 1].set(vals)
    out = out.at[0, p + 2].set(resid)
    return out


def fused_finish(
    g,
    k: int,
    oversample: int = _DEF_OVERSAMPLE,
    iters: int = _DEF_ITERS,
    seed: int = 0,
    timer=None,
    resid_warn: float = 1e-3,
    max_retries: int = 1,
):
    """(N, N) Gramian → top-k principal coordinates in ONE dispatch.

    The finish half of the fused path — the piece ``VariantsPcaDriver``
    runs after the streamed packed accumulation (``--pca-mode auto`` /
    ``fused``). One jit (centering + CholeskyQR subspace eig + row sums),
    one packed host readback. Same coordinate semantics as
    ``pcoa(g, k)``; convergence and spectral-gap degeneracy are checked
    host-side on the returned values.

    ``resid_warn`` is a CONVERGENCE TARGET, not just a warning bar (the
    driver threads ``--eig-tol`` into it): when the max top-k relative
    Ritz residual exceeds it, the sweep re-runs with doubled iterations
    up to ``max_retries`` times (G is still device-resident, so a retry
    is one more dispatch — rare, and only marginal-spectrum cohorts pay
    it) before warning loudly. Eigenvector error is O(resid / gap).

    Returns ``(coords (N, k), vals (k,) float64, row_sums (N,))``.
    """
    from spark_examples_tpu import obs
    from spark_examples_tpu.obs.xla import record_compiled

    n = int(g.shape[0])
    p = min(n, k + oversample)
    gd = jnp.asarray(g)
    for attempt in range(max_retries + 1):
        run_iters = iters << attempt
        key = jax.random.PRNGKey(seed)
        record_compiled(
            "fused_finish", _finish_jit, gd, k, oversample, run_iters, key
        )
        with obs.span(
            "fused_finish", n=n, k=k, iters=run_iters, attempt=attempt
        ):
            out = np.asarray(_finish_jit(gd, k, oversample, run_iters, key))
        resid = float(out[0, p + 2])
        if not np.isfinite(resid):
            # Panel collapse is deterministic for a given (G, seed):
            # retrying with doubled iterations recompiles and re-runs a
            # dispatch guaranteed to produce the same NaN. Fall straight
            # through to the non-finite raise below.
            break
        if resid <= resid_warn:
            break
        if attempt < max_retries:
            if timer is not None:
                timer.note(
                    f"fused eig residual {resid:.2e} > {resid_warn:g} "
                    f"after {run_iters} iterations — retrying doubled"
                )
    vecs = out[:, :p]
    row_sums = out[:, p]
    vals = out[:p, p + 1].astype(np.float64)
    if not np.isfinite(vals).all() or not np.isfinite(resid):
        # A NaN here means the panel factorization collapsed (advisor
        # round 4: it must never flow silently into the gap check and
        # out through emit_result as all-NaN coordinates).
        raise FloatingPointError(
            "fused eigendecomposition produced non-finite Ritz values "
            f"(vals={vals[: k + 1]}, resid={resid}); the cohort's "
            "centered Gramian is numerically degenerate — rerun with "
            "--pca-mode stream (dense eigh) or --precise"
        )
    if timer is not None:
        timer.note(
            f"fused eig residual {resid:.2e} ({run_iters} iterations)"
        )
    if resid > resid_warn:
        warnings.warn(
            f"fused subspace iteration residual {resid:.2e} exceeds "
            f"{resid_warn:g} after {run_iters} iterations — coordinates "
            "may not have converged to dense-eigh accuracy on this "
            "cohort; use --pca-mode stream (dense eigh) or --precise to "
            "cross-check",
            EigResidualWarning,
            stacklevel=2,
        )
    check_spectral_gap(vals, k, timer=timer)
    return vecs[:, :k], vals[:k], row_sums


def fused_forward(x, k: int = 2):
    """The shipped flagship composition as ONE jittable function.

    int8 0/1 indicators → integer-MXU Gramian → fused finish (centering
    + CholeskyQR subspace eig) → (N, k) coordinates, with the SAME sweep
    defaults ``--pca-mode auto`` ships — the driver contract
    (``__graft_entry__.entry``) compiles exactly this, so the certified
    path and the product path cannot drift.
    """
    from spark_examples_tpu.ops.gramian import mxu_cross_product

    out = _finish_jit(
        mxu_cross_product(x, jnp.float32, jnp.int8),
        k,
        _DEF_OVERSAMPLE,
        _DEF_ITERS,
        jax.random.PRNGKey(0),
    )
    return out[:, :k]


def pcoa_fused_blocks(
    blocks,
    n_samples: int,
    k: int,
    oversample: int = _DEF_OVERSAMPLE,
    iters: int = _DEF_ITERS,
    seed: int = 0,
    compute_dtype=None,
    device=None,
    timer=None,
):
    """0/1 indicator blocks → top-k principal coordinates, fully fused.

    THE shipped fast path (and ``bench.py``'s ``fused`` mode): the blocks
    stream through the bit-packed double-buffered accumulator
    (:func:`~spark_examples_tpu.ops.gramian.gramian_blockwise` with
    ``packed=True`` — pack, transfer, and matmul overlap; G accumulates
    donated in HBM), then :func:`fused_finish` runs centering + subspace
    eig + row sums in one dispatch with one packed readback. The variant
    axis is unbounded (HBM holds G plus one block transient, never the
    cohort), which is what lets the same program run at all-autosomes V.

    Returns ``(coords (N, k), vals (k,), row_sums (N,))``.
    """
    g = gramian_blockwise(
        blocks,
        n_samples,
        packed=True,
        compute_dtype=compute_dtype,
        device=device,
    )
    return fused_finish(
        g, k, oversample=oversample, iters=iters, seed=seed, timer=timer
    )


def pcoa_fused_packed(
    x_packed: np.ndarray,
    n_bits: int,
    k: int,
    chunk_bits: int = 65536,
    oversample: int = _DEF_OVERSAMPLE,
    iters: int = _DEF_ITERS,
    seed: int = 0,
    compute_dtype=None,
    device=None,
    timer=None,
):
    """Packed indicator matrix → top-k principal coordinates.

    Whole-cohort API over an already-packed ``(N, ⌈V/8⌉)`` uint8 matrix
    (:func:`pack_indicator_block` output): the packed variant axis is cut
    into ``chunk_bits``-wide pieces which stream through the
    double-buffered feed into donated accumulate dispatches — transfer of
    chunk i+1 overlaps chunk i's matmul — then one
    :func:`fused_finish` dispatch. Prefer :func:`pcoa_fused_blocks` when
    the cohort is still in unpacked blocks (it overlaps the host-side
    pack as well); this entry point serves callers that keep a packed
    cohort resident (tests, re-analysis at different k).

    Args:
      x_packed: ``(N, ⌈V/8⌉)`` uint8 packed 0/1 indicators, whole cohort.
      n_bits: V — the true variant count (pad bits beyond it are zero and
        inert in the Gramian).
      chunk_bits: variant bits per accumulate dispatch; bounds the
        unpacked (N, chunk_bits) int8 HBM transient and sets the
        transfer/compute overlap granularity.

    Returns:
      ``(coords (N, k) np.ndarray, vals (k,) np.ndarray)`` — same
      semantics as ``pcoa(gramian(X), k)``.
    """
    x_packed = np.asarray(x_packed)
    chunk_bits = int(min(chunk_bits, max(8, n_bits)))
    chunk_bits = ((chunk_bits + 7) // 8) * 8
    chunk_bytes = chunk_bits // 8

    def chunks():
        for off in range(0, x_packed.shape[1], chunk_bytes):
            piece = x_packed[:, off : off + chunk_bytes]
            if piece.shape[1] != chunk_bytes:
                # Zero bytes unpack to zero columns — inert in X @ X.T —
                # and keep every accumulate step on one compiled shape.
                piece = np.pad(
                    piece, ((0, 0), (0, chunk_bytes - piece.shape[1]))
                )
            yield piece

    g = gramian_blockwise(
        chunks(),
        x_packed.shape[0],
        compute_dtype=compute_dtype,
        device=device,
        packed=True,
        prepacked=True,
    )
    coords, vals, _ = fused_finish(
        g, k, oversample=oversample, iters=iters, seed=seed, timer=timer
    )
    return coords, vals
