"""Single-dispatch fused PCoA: packed X → coordinates in ONE device program.

Why this exists (round-4 roofline work): through the axon relay the PCoA
phase is **link-bound** — the measured host→device path moves ~48 MB/s and
every synchronous host-visible result costs a ~65 ms roundtrip, while the
device-side compute for the whole bench workload (Gramian + centering +
top-k eig at N=2504, V=65536) is ~10 ms. The streamed production path
(``gramian_blockwise`` + ``pcoa``) pays one put per block plus several
dispatch/readback roundtrips; this path pays the minimum possible:

    1 × device_put of the bit-packed X  (the irreducible bytes)
    1 × jit dispatch                     (unpack → Gramian → center → eig)
    1 × readback of the (N, k) coordinates

On links where latency and per-transfer overheads dominate (any remote
tunnel; also multi-process launches amortizing dispatch), this is the
fastest shape the computation can take; on a local PCIe link it simply ties
the streamed path, because both then sit at the same transfer roofline.

The top-k eigendecomposition inside the program is randomized subspace
iteration with **CholeskyQR** panel orthonormalization: ``qr`` on TPU
lowers to sequential Householder steps (measured 2.4× slower end-to-end),
whereas CholeskyQR is two MXU matmuls plus a (p, p) Cholesky + triangular
solve — numerically fine here because panels are re-orthonormalized every
iteration and PCoA spectra are mild (κ(panel Gram) ≈ (λ₁/λ_p)² per sweep;
the f32 limit ~2^12 dwarfs realistic population-structure ratios, and the
parity gate below would catch a violation loudly).

Semantics match :func:`spark_examples_tpu.ops.pcoa.pcoa` exactly: raw
sign-normalized eigenvectors of the double-centered Gramian ordered by
|λ| descending (the MLlib composition equivalence — pcoa.py module
docstring; reference ``VariantsPca.scala:198-231``). Accuracy vs dense
``eigh`` is set by ``iters``; the defaults land ≤1e-4 max coordinate error
on structured (population-structure) cohorts and are verified against the
f64 MLlib-literal golden in tests and in ``bench.py``. The spectral-gap
degeneracy check runs host-side on the returned Ritz values, exactly as
the dense path's (:func:`~spark_examples_tpu.ops.pcoa.check_spectral_gap`).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from spark_examples_tpu.ops.centering import double_center
from spark_examples_tpu.ops.gramian import (
    pack_indicator_block,
    resolve_gramian_compute_dtype,
    unpack_indicator_block,
)
from spark_examples_tpu.ops.pcoa import (
    check_spectral_gap,
    normalize_eigvec_signs,
)

__all__ = ["pcoa_fused_packed", "subspace_eig_cholqr"]


def subspace_eig_cholqr(c, k: int, oversample: int = 8, iters: int = 16,
                        key=None):
    """Top-|λ| eigenpairs of symmetric ``c`` — jittable, MXU-only inner loop.

    Returns ``(vecs (N, k+oversample), vals (k+oversample,))`` |λ|-ordered
    and sign-normalized; callers slice to k after the host-side gap check.
    """
    n = c.shape[0]
    p = min(n, k + oversample)
    if key is None:
        key = jax.random.PRNGKey(0)
    q = jax.random.normal(key, (n, p), c.dtype)
    eye = jnp.eye(p, dtype=c.dtype)

    # TPU matmuls default to bf16 MXU passes — fine for the int8-exact
    # Gramian, fatal for eigenvector refinement (the iteration stalls at
    # ~1e-4 instead of converging to ~3e-7, measured on chip round 4).
    # Panel matmuls are O(N²p) — forcing f32-equivalent precision costs
    # ~3× on a term that is ~1% of the phase.
    with jax.default_matmul_precision("float32"):

        def body(q, _):
            y = c @ q
            # CholeskyQR: orthonormalize through the (p, p) Gram factor.
            # The tiny jitter keeps the factorization alive when a panel
            # column underflows (rank-deficient C); such columns are
            # discarded by the |λ| ordering anyway.
            r = jnp.linalg.cholesky(
                y.T @ y + jnp.finfo(c.dtype).tiny * eye
            )
            q = jax.lax.linalg.triangular_solve(
                r, y, left_side=False, lower=True, transpose_a=True
            )
            return q, None

        q, _ = jax.lax.scan(body, q, None, length=iters)
        y = c @ q
        b = q.T @ y
        w, u = jnp.linalg.eigh(b)
        order = jnp.argsort(-jnp.abs(w))
        return normalize_eigvec_signs(q @ u[:, order]), w[order]


@partial(
    jax.jit,
    static_argnames=("n_bits", "chunk_bits", "k", "oversample", "iters",
                     "compute_dtype"),
)
def _fused_jit(xp, n_bits, chunk_bits, k, oversample, iters, compute_dtype,
               key):
    n = xp.shape[0]
    n_chunks = -(-n_bits // chunk_bits)
    # Chunk the packed variant axis and scan, so the unpacked int8
    # transient is (N, chunk_bits) instead of (N, V) — bounds HBM at
    # all-autosomes V while staying one dispatch.
    xc = xp.reshape(n, n_chunks, chunk_bits // 8).transpose(1, 0, 2)

    def accum(g, chunk):
        x = unpack_indicator_block(chunk, chunk_bits)
        if compute_dtype == jnp.int8:
            prod = jnp.einsum(
                "nv,mv->nm", x, x, preferred_element_type=jnp.int32
            )
        else:
            xf = x.astype(compute_dtype)
            # Float MXU path: accumulate the exact 0/1 product in its own
            # dtype, then cast the integral counts into the int32
            # accumulator (exact below 2^24 per entry, as everywhere).
            prod = jnp.einsum(
                "nv,mv->nm", xf, xf, preferred_element_type=compute_dtype
            ).astype(jnp.int32)
        return g + prod, None

    g, _ = jax.lax.scan(accum, jnp.zeros((n, n), jnp.int32), xc)
    c = double_center(g.astype(jnp.float32))
    vecs, vals = subspace_eig_cholqr(
        c, k, oversample=oversample, iters=iters, key=key
    )
    return vecs, vals


def pcoa_fused_packed(
    x_packed: np.ndarray,
    n_bits: int,
    k: int,
    chunk_bits: int = 65536,
    oversample: int = 8,
    iters: int = 28,
    seed: int = 0,
    compute_dtype=None,
    device=None,
    timer=None,
):
    """Packed indicator matrix → top-k principal coordinates, one dispatch.

    Args:
      x_packed: ``(N, ⌈V/8⌉)`` uint8, :func:`pack_indicator_block` output
        for the WHOLE cohort (all variant blocks concatenated).
      n_bits: V — the true variant count (pad bits beyond it are zero and
        inert).
      k: number of principal coordinates.
      chunk_bits: variant-axis chunk per scan step; bounds the unpacked
        (N, chunk) int8 transient in HBM.
      compute_dtype: MXU dtype policy; default resolves via
        :func:`resolve_gramian_compute_dtype` (int8 integer-MXU).

    Returns:
      ``(coords (N, k) np.ndarray, vals (k,) np.ndarray)`` — same
      semantics as ``pcoa(gramian(X), k)``.
    """
    x_packed = np.asarray(x_packed)
    compute_dtype = resolve_gramian_compute_dtype(
        jnp.int8, jnp.float32, compute_dtype
    )
    chunk_bits = int(min(chunk_bits, max(8, n_bits)))
    chunk_bits = ((chunk_bits + 7) // 8) * 8
    chunk_bytes = chunk_bits // 8
    n_chunks = -(-x_packed.shape[1] // chunk_bytes)
    padded_cols = n_chunks * chunk_bytes
    if padded_cols != x_packed.shape[1]:
        # Zero bytes unpack to zero columns — inert in X @ X.T.
        x_packed = np.pad(
            x_packed, ((0, 0), (0, padded_cols - x_packed.shape[1]))
        )
    xpd = jax.device_put(x_packed, device)
    vecs, vals = _fused_jit(
        xpd,
        n_chunks * chunk_bits,
        chunk_bits,
        k,
        oversample,
        iters,
        compute_dtype,
        jax.random.PRNGKey(seed),
    )
    vecs = np.asarray(vecs)
    vals = np.asarray(vals, dtype=np.float64)
    check_spectral_gap(vals, k, timer=timer)
    return vecs[:, :k], vals[:k]
