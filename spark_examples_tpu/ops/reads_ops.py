"""Reads kernels: coverage, per-base depth, base-frequency pileup.

The reference's reads examples are per-base scalar loops shuffled through
Spark (``SearchReadsExample.scala:138-164`` flatMaps every read into one
(position, 1) pair *per base* and reduceByKey's them — O(total bases)
shuffle records). TPU-native formulations:

- **per-base depth** — a difference array: +1 at each read start, −1 past
  its end, inclusive prefix sum. O(reads) scatter + O(region) cumsum, no
  per-base materialization at all.
- **base frequencies** — one scatter-add of (position-offset, base-code)
  pairs into a (region, 5) count table; frequencies are one row-normalize.
  Quality masking happens in the same gather (no host filtering loop).

Both are static-shape, fully on the VPU, and windowed by the shard manifest
so whole-chromosome regions stream through fixed-size programs.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "per_base_depth",
    "base_frequency_table",
    "BASE_CODES",
    "encode_bases",
]

# Base → column: A C G T N/other. The reference keys its frequency maps by
# raw char (SearchReadsExample.scala:219-238); N is rare but countable.
BASE_CODES = {"A": 0, "C": 1, "G": 2, "T": 3, "N": 4}
_BASE_LUT = np.full(256, 4, dtype=np.int8)
for _b, _c in BASE_CODES.items():
    _BASE_LUT[ord(_b)] = _c
    _BASE_LUT[ord(_b.lower())] = _c


def encode_bases(seq: str) -> np.ndarray:
    """ASCII sequence → int8 codes (vectorized byte lookup)."""
    return _BASE_LUT[np.frombuffer(seq.encode("ascii"), dtype=np.uint8)]


@partial(jax.jit, static_argnames=("region_len",))
def per_base_depth(starts, lengths, region_len):
    """Read depth over a region window via difference array + cumsum.

    Args:
      starts: (R,) int32 read start offsets relative to the window (may be
        negative for reads starting before the window — clipped).
      lengths: (R,) int32 aligned-sequence lengths (0 = padding slot).
      region_len: static window size.

    Returns:
      (region_len,) int32 depth. Matches the reference's semantics of one
      count per aligned base (cigar-less, as the reference's own TODO notes,
      SearchReadsExample.scala:152).
    """
    starts = starts.astype(jnp.int32)
    ends = starts + lengths.astype(jnp.int32)
    lo = jnp.clip(starts, 0, region_len)
    hi = jnp.clip(ends, 0, region_len)
    valid = (lengths > 0) & (hi > lo)
    diff = jnp.zeros((region_len + 1,), jnp.int32)
    diff = diff.at[jnp.where(valid, lo, region_len)].add(
        jnp.where(valid, 1, 0)
    )
    diff = diff.at[jnp.where(valid, hi, region_len)].add(
        jnp.where(valid, -1, 0)
    )
    return jnp.cumsum(diff[:-1])


@partial(jax.jit, static_argnames=("region_len",))
def base_frequency_table(starts, base_codes, quals, min_base_qual, region_len):
    """Per-position base counts with quality masking, one scatter-add.

    Args:
      starts: (R,) int32 read start offsets relative to the window.
      base_codes: (R, L) int8 encoded bases (5 = beyond-sequence padding).
      quals: (R, L) int32 per-base qualities (−1 where absent: the
        reference skips bases past the quality array,
        SearchReadsExample.scala:225).
      min_base_qual: scalar threshold.
      region_len: static window size.

    Returns:
      (region_len, 5) int32 counts; divide by row sums for frequencies.
    """
    r, l = base_codes.shape
    pos = starts[:, None].astype(jnp.int32) + jnp.arange(l, dtype=jnp.int32)
    valid = (
        (base_codes >= 0)
        & (base_codes < 5)
        & (quals >= min_base_qual)
        & (pos >= 0)
        & (pos < region_len)
    )
    flat_pos = jnp.where(valid, pos, region_len).reshape(-1)
    flat_code = jnp.clip(base_codes, 0, 4).astype(jnp.int32).reshape(-1)
    counts = jnp.zeros((region_len + 1, 5), jnp.int32)
    counts = counts.at[flat_pos, flat_code].add(
        jnp.where(valid.reshape(-1), 1, 0)
    )
    return counts[:region_len]
