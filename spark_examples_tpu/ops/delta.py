"""Exact rank-k sample corrections for cached Gramians.

The serving tier's incremental-delta path (``serving/deltas.py``): when a
submitted cohort differs from a cached one by a handful of samples, the
cached G is algebraically updatable instead of re-accumulated — the
blockwise discipline of *Fast PCA of genotype matrices in Julia* (arxiv
1808.03374) applied to the 0/1 indicator Gramian, and the kernel-
decomposition observation of arxiv 1909.00954 that the same carrier
windows serve any per-window update rule. With X the full-cohort 0/1
indicator matrix and S/A the target/ancestor sample sets:

- entries over ``S ∩ A`` are UNCHANGED (``G[i, j]`` depends only on
  samples i and j — the AF filter reads the variant record, never the
  cohort), so they GATHER from the cached G;
- rows/columns of added samples ``D = S \\ A`` are a rank-``|D|``
  correction ``C = Σ_v x_v^S (x_v^D)ᵀ`` over exactly the variants some
  touched sample carries — built here by the same OOB-drop scatter idiom
  as :mod:`spark_examples_tpu.ops.sparse`, with a ±1 sign;
- removed samples contribute by OMISSION (their rows/columns simply do
  not gather); the signed scatter's ``sign=-1`` additionally supports
  subtracting a sample set's contributions in place, pinned equal-and-
  opposite to ``sign=+1`` by test.

Every update is an exact integer count in f32 (far below 2^24), so the
delta result is **bit-identical** to a from-scratch accumulation of the
target cohort — the contract the serving tests pin, and what lets the
checksum guard upstream fall back to cold on ANY doubt without ever
changing results.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Iterable, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from spark_examples_tpu.ops.gramian import (
    mxu_cross_product_pair,
    resolve_gramian_compute_dtype,
)
from spark_examples_tpu.ops.sparse import (
    DEFAULT_SPARSE_DENSITY_THRESHOLD,
    SCATTER_CHUNK_VARIANTS,
    _carrier_bucket,
    padded_carrier_matrix,
)

__all__ = [
    "delta_gramian",
    "sample_correction",
    "signed_scatter_pairs",
]


@partial(jax.jit, donate_argnums=(0,), static_argnames=("sign",))
def _signed_scatter_jit(
    acc: Any, row_idx: Any, col_idx: Any, sign: int
) -> Any:
    """``acc[row_idx[v,a], col_idx[v,b]] += sign`` for every (v, a, b),
    out-of-bounds indices dropped — the ±1 twin of
    :func:`spark_examples_tpu.ops.sparse.scatter_pairs_chunked`, chunked
    under ``lax.scan`` so the broadcast update transient stays bounded
    at ``chunk · k_row · k_col`` elements. ``sign`` is a static ±1 int;
    the update value is the exact integer ``sign`` in ``acc.dtype``.
    """
    unit = jnp.asarray(sign, acc.dtype)
    shape_r = (
        row_idx.shape[0] // SCATTER_CHUNK_VARIANTS,
        SCATTER_CHUNK_VARIANTS,
        row_idx.shape[1],
    )
    shape_c = (shape_r[0], SCATTER_CHUNK_VARIANTS, col_idx.shape[1])

    def body(g: Any, chunk: Any) -> Any:
        ci, cj = chunk
        return (
            g.at[ci[:, :, None], cj[:, None, :]].add(unit, mode="drop"),
            None,
        )

    acc, _ = jax.lax.scan(
        body,
        acc,
        (row_idx.reshape(shape_r), col_idx.reshape(shape_c)),
    )
    return acc


def signed_scatter_pairs(
    acc: Any, row_idx: Any, col_idx: Any, sign: int = 1
) -> Any:
    """Public entry: scatter ``±1`` at every (row, col) carrier pair of
    every variant, OOB dropped. ``row_idx``/``col_idx`` are padded
    carrier matrices (``padded_carrier_matrix``) whose variant axes must
    match and be a multiple of ``SCATTER_CHUNK_VARIANTS``."""
    if sign not in (1, -1):
        raise ValueError(f"sign must be +1 or -1, got {sign}")
    if row_idx.shape[0] != col_idx.shape[0]:
        raise ValueError(
            f"row/col carrier matrices disagree on variants: "
            f"{row_idx.shape[0]} vs {col_idx.shape[0]}"
        )
    return _signed_scatter_jit(acc, row_idx, col_idx, sign)


def _pow2_rows(rows: int) -> int:
    """Variant-axis padding: a power-of-two multiple of the scan chunk,
    so the jit geometry count stays O(log V) across delta jobs instead
    of one executable per 256-variant increment."""
    padded = SCATTER_CHUNK_VARIANTS
    while padded < rows:
        padded *= 2
    return padded


@partial(jax.jit, static_argnames=("sign", "compute_dtype"))
def _dense_correction_jit(
    xr: Any, xc: Any, sign: int, compute_dtype: Any
) -> Any:
    prod = mxu_cross_product_pair(xr, xc, jnp.float32, compute_dtype)
    return prod * jnp.asarray(sign, jnp.float32)


def _dense_correction(
    rows_full: np.ndarray,
    row_lens: np.ndarray,
    cols_full: np.ndarray,
    col_lens: np.ndarray,
    n_rows: int,
    n_cols: int,
    sign: int,
) -> np.ndarray:
    """MXU route for the correction: densify the touched variants'
    carriers into 0/1 panels and take ONE ``X_S @ X_Dᵀ`` cross product
    — exact integer counts times an exact ±1, so bit-identical to the
    scatter route (the same argument as the sparse engine's per-window
    density gate, whose threshold this module reuses). Variant axis
    pads to a power-of-two bucket for executable stability; pad columns
    are zero and inert."""
    v_f = int(row_lens.size)
    v_pad = max(_carrier_bucket(v_f), SCATTER_CHUNK_VARIANTS)
    xr = np.zeros((n_rows, v_pad), dtype=np.int8)
    row_cols = np.repeat(np.arange(v_f, dtype=np.int64), row_lens)
    in_rows = rows_full < n_rows  # drop the OOB sentinels
    xr[rows_full[in_rows], row_cols[in_rows]] = 1
    xc = np.zeros((n_cols, v_pad), dtype=np.int8)
    col_cols = np.repeat(np.arange(v_f, dtype=np.int64), col_lens)
    xc[cols_full, col_cols] = 1
    compute_dtype = resolve_gramian_compute_dtype(
        jnp.int8, jnp.float32
    )
    return np.asarray(
        _dense_correction_jit(xr, xc, sign, compute_dtype)
    )


def sample_correction(
    windows: Iterable[Tuple[np.ndarray, np.ndarray]],
    row_of_full: np.ndarray,
    col_of_full: np.ndarray,
    n_rows: int,
    n_cols: int,
    sign: int = 1,
    density_threshold: float = DEFAULT_SPARSE_DENSITY_THRESHOLD,
) -> np.ndarray:
    """Rank-k correction ``C[r, t] = Σ_v x_v[r] · x_v[t]`` over exactly
    the touched variants of a full-frame CSR window stream.

    ``row_of_full`` / ``col_of_full`` map FULL-frame sample indices to
    target-row / touched-column positions, with a value ``>= n_rows`` /
    ``>= n_cols`` acting as the drop sentinel (OOB scatter semantics —
    same idiom as the sparse engine's carrier pad). Only variants with
    at least one in-bounds column carrier contribute, so the host filter
    touches every window once (vectorized numpy) while the device work
    pays only for the touched variants' carriers. The touched set then
    routes by DENSITY exactly like the sparse engine's windows: below
    the threshold it rides the ±1 OOB-drop scatter; at or above it, the
    densified MXU cross product — bit-identical either way (exact
    integer counts). Returns an exact-integer-count f32
    ``(n_rows, n_cols)`` array.
    """
    row_of_full = np.asarray(row_of_full, dtype=np.int64)
    col_of_full = np.asarray(col_of_full, dtype=np.int64)
    r_parts: List[np.ndarray] = []
    c_parts: List[np.ndarray] = []
    rlen_parts: List[np.ndarray] = []
    clen_parts: List[np.ndarray] = []
    for window_idx, lens in windows:
        window_idx = np.asarray(window_idx, dtype=np.int64)
        lens = np.asarray(lens, dtype=np.int64)
        if window_idx.size == 0:
            continue
        cols = col_of_full[window_idx]
        hit = cols < n_cols
        if not hit.any():
            continue
        row_of = np.repeat(np.arange(lens.size, dtype=np.int64), lens)
        touched_count = np.bincount(
            row_of, weights=hit, minlength=lens.size
        ).astype(np.int64)
        touched = touched_count > 0
        keep = touched[row_of]
        r_parts.append(row_of_full[window_idx[keep]])
        # The column side keeps ONLY in-bounds (touched) carriers per
        # variant: its carrier bucket is then bounded by k (≤ the
        # delta-max bound), not by the variant's full carrier count —
        # an ~k_max/k smaller scatter transient for the same result
        # (the dropped entries were all OOB sentinels anyway).
        c_parts.append(cols[hit])
        rlen_parts.append(lens[touched])
        clen_parts.append(touched_count[touched])
    if not rlen_parts:
        return np.zeros((n_rows, n_cols), dtype=np.float32)
    rows_full = np.concatenate(r_parts)
    cols_full = np.concatenate(c_parts)
    row_lens = np.concatenate(rlen_parts)
    col_lens = np.concatenate(clen_parts)
    density = float(row_lens.sum()) / max(
        1, n_rows * int(row_lens.size)
    )
    # The touched-column axis pads to a power-of-two bucket so the
    # correction executable is stable across delta sizes (a ±7 and a
    # ±8 job share one compile); pad columns receive nothing — the
    # host filter already dropped every out-of-set carrier — and are
    # sliced off before returning.
    n_cols_pad = _carrier_bucket(n_cols)
    if density >= density_threshold:
        return _dense_correction(
            rows_full, row_lens, cols_full, col_lens,
            n_rows, n_cols_pad, sign,
        )[:, :n_cols]
    n_pad = _pow2_rows(row_lens.size)
    # Row sentinel >= n_rows and column sentinel >= the padded column
    # bound both drop; each side carries its own power-of-two carrier
    # bucket.
    row_mat = padded_carrier_matrix(
        rows_full, row_lens, sentinel=n_rows, n_rows=n_pad,
        k_bucket=_carrier_bucket(int(row_lens.max())),
    )
    col_mat = padded_carrier_matrix(
        cols_full, col_lens, sentinel=n_cols_pad, n_rows=n_pad,
        k_bucket=_carrier_bucket(int(col_lens.max())),
    )
    acc = jnp.zeros((n_rows, n_cols_pad), dtype=jnp.float32)
    return np.asarray(
        signed_scatter_pairs(acc, row_mat, col_mat, sign)
    )[:, :n_cols]


def delta_gramian(
    cached_g: np.ndarray,
    ancestor_full: np.ndarray,
    target_full: np.ndarray,
    n_full: int,
    windows: Iterable[Tuple[np.ndarray, np.ndarray]],
) -> np.ndarray:
    """Cached ancestor G → target-cohort G by gather + rank-k touch-up.

    ``ancestor_full``/``target_full`` are the full-frame sample indices
    of the ancestor/target cohorts IN FRAME ORDER (row i of the
    ancestor G is sample ``ancestor_full[i]``). ``windows`` is a
    full-frame CSR window stream covering the cohort's variants (the
    serving tier feeds its per-base-key window cache, or re-streams the
    source). Bit-identical to a from-scratch accumulation of the target
    cohort — every entry is the same exact integer count.
    """
    ancestor_full = np.asarray(ancestor_full, dtype=np.int64)
    target_full = np.asarray(target_full, dtype=np.int64)
    cached_g = np.asarray(cached_g, dtype=np.float32)
    if cached_g.shape != (ancestor_full.size, ancestor_full.size):
        raise ValueError(
            f"cached G shape {cached_g.shape} does not match ancestor "
            f"frame size {ancestor_full.size}"
        )
    n_t = int(target_full.size)
    anc_of_full = np.full(n_full, -1, dtype=np.int64)
    anc_of_full[ancestor_full] = np.arange(
        ancestor_full.size, dtype=np.int64
    )
    common_t = np.nonzero(anc_of_full[target_full] >= 0)[0]
    added_t = np.nonzero(anc_of_full[target_full] < 0)[0]
    g = np.zeros((n_t, n_t), dtype=np.float32)
    if common_t.size:
        anc_idx = anc_of_full[target_full[common_t]]
        g[np.ix_(common_t, common_t)] = cached_g[np.ix_(anc_idx, anc_idx)]
    if added_t.size:
        # Full-frame → target-row map (sentinel n_t drops non-cohort
        # carriers) and full-frame → added-column map (sentinel k).
        row_of_full = np.full(n_full, n_t, dtype=np.int64)
        row_of_full[target_full] = np.arange(n_t, dtype=np.int64)
        k = int(added_t.size)
        col_of_full = np.full(n_full, k, dtype=np.int64)
        col_of_full[target_full[added_t]] = np.arange(k, dtype=np.int64)
        corr = sample_correction(
            windows, row_of_full, col_of_full, n_t, k, sign=1
        )
        g[:, added_t] = corr
        g[added_t, :] = corr.T
    return g
