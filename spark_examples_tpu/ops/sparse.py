"""Sparse-aware Gramian accumulation straight from CSR carrier windows.

The dense path (:mod:`spark_examples_tpu.ops.gramian`) densifies every
variant window into a 0/1 indicator block and rides the MXU — O(N²·V)
matmul work and an (N, V_blk) transient regardless of how empty the
block is. At biobank shape (N=100k-1M, ~98% zeros) that transient and
the matmul are the wall. The decomposition papers (arxiv 1909.00954,
arxiv 1808.03374) compute G = XᵀX from the sparse representation
without ever densifying; this module is that path for the 0/1
indicator Gramian:

    G[i, j] += |{v : i ∈ carriers(v) and j ∈ carriers(v)}|

accumulated as ONE scatter-add per window, directly from the
``(indices, lens)`` CSR windows the ingest tier already produces
(:func:`spark_examples_tpu.arrays.blocks.csr_windows`) — no densify, no
bit-pack, no (N, V_blk) transient. Work is O(Σ k_v²) (k_v = carriers of
variant v) instead of O(N²·V_blk): at density d the ratio is ~d², which
is what makes the 98%-zeros regime tractable at all.

Formulation (one-hot-free segment scatter): each window's ragged carrier
lists are right-padded into a ``(V_blk, k_max)`` int32 index matrix with
an out-of-range sentinel; the jitted kernel scatter-adds ``+1`` at every
``(idx[v, a], idx[v, b])`` pair with OOB-drop semantics, so sentinel
pairs vanish and the accumulation stays integer-exact (every update is
an exact +1 in f32, the same exactness argument as the dense path —
bit-identical G, pinned by tests). The scatter runs in fixed-size
variant chunks under ``lax.scan`` so the update transient is bounded by
``chunk · k_max²`` — never window-sized.

Density routing: genuinely dense windows (common variants) would pay
k_max² ≈ (dN)² per variant here while the MXU path pays N·V_blk — the
scatter loses above a few percent density. ``sparse_gramian_blockwise``
therefore routes each window by its own density: strictly below the
threshold it scatters straight from CSR; at or above it densifies +
bit-packs into the existing MXU accumulator. Both routes add exact
integer counts, so the mix is bit-identical to either pure path
(PERFORMANCE.md has the decision-log entry for the default).
"""

from __future__ import annotations

from functools import partial
from typing import Iterable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "DEFAULT_POD_COALESCE_VARIANTS",
    "DEFAULT_SPARSE_DENSITY_THRESHOLD",
    "SCATTER_CHUNK_VARIANTS",
    "dense_panel_width",
    "padded_carrier_matrix",
    "scatter_pairs_chunked",
    "sparse_gramian_accumulate",
    "sparse_gramian_blockwise",
    "window_density",
    "window_route",
]

# Dense/sparse switch: windows with density STRICTLY below this scatter
# straight from CSR; at or above it they densify onto the MXU path. The
# default is the measured CPU crossover region with margin — see the
# PERFORMANCE.md decision-log entry (sparse wins on work at any d < 1,
# but a scatter update costs ~10-100x a matmul MAC, so the honest
# crossover sits at a few percent density; biobank cohorts at ~2% sit
# under it, 1000-Genomes common variants at ~10% over it).
DEFAULT_SPARSE_DENSITY_THRESHOLD = 0.02

# Variant rows scattered per lax.scan step: bounds the broadcast update
# transient at chunk * k_max^2 elements (e.g. 256 * 256^2 f32 = 67 MB)
# instead of the whole window's V_blk * k_max^2.
SCATTER_CHUNK_VARIANTS = 256

# Pod-sparse gang coalescing target (the pipelined carrier-allgather
# protocol in parallel/sharded._synced_carrier_stream): consecutive
# scatter-route windows merge into one protocol step until their
# variant-row total reaches this, so tiny windows (tail windows, small
# shards) amortize one header + one carrier exchange instead of paying
# per-window exchange latency. Windows at the normal block width
# (DEFAULT_BLOCK_VARIANTS) already exceed it — coalescing only engages
# where it pays. 0/1 disables. Aligned with SCATTER_CHUNK_VARIANTS so a
# full gang fills at least one scan/kernel chunk.
DEFAULT_POD_COALESCE_VARIANTS = 256

_MIN_CARRIER_BUCKET = 8


def window_density(lens: np.ndarray, n_samples: int) -> float:
    """nnz / (N · V) for one CSR window (0.0 for an empty window)."""
    lens = np.asarray(lens)
    if lens.size == 0 or n_samples == 0:
        return 0.0
    return float(lens.sum()) / (n_samples * lens.size)


def window_route(
    lens: np.ndarray, n_samples: int, density_threshold: float
) -> str:
    """``"scatter"`` | ``"dense"`` for one window — THE switch both the
    single-device and mesh-sharded accumulators consult, so the two can
    never disagree on a boundary case. Density exactly AT the threshold
    routes dense (the MXU side of the tie), pinned by test.

    Two gates, both required for scatter: the MEAN density (total work,
    O(Σk²) pairs) and the MAX per-variant carrier fraction — scatter
    cost and its update transient scale with k_max², so ONE common
    variant (k ≈ N/4) buried in an otherwise-rare window would blow the
    padded carrier matrix to k_bucket ≈ N while the mean density still
    whispers "sparse". Such a window routes dense, where the MXU cost
    is flat in k.
    """
    lens = np.asarray(lens)
    if window_density(lens, n_samples) >= density_threshold:
        return "dense"
    if (
        lens.size
        and n_samples
        and int(lens.max()) / n_samples >= density_threshold
    ):
        return "dense"
    return "scatter"


def _carrier_bucket(k: int) -> int:
    """Round a window's max carrier count up to a power of two (min 8):
    the padded index matrix's column count is a static jit shape, so
    bucketing bounds executable count at O(log N) per block width."""
    bucket = _MIN_CARRIER_BUCKET
    while bucket < k:
        bucket *= 2
    return bucket


def dense_panel_width(rows: int, block_variants: int) -> int:
    """Padded variant width for one DENSE-route window's panel.

    Historically every dense window padded to the full block width so
    the packed MXU executable shape stayed stable — but that makes a
    512-variant window on an 8192-variant block pay 16× its MXU work in
    inert zero columns (measured dominant in the MULTICHIP pod bench,
    PERFORMANCE.md decision log). The power-of-two bucket (min 8, capped
    at the block width — ``csr_windows`` never yields wider) keeps the
    executable count O(log V) by the same argument as
    :func:`_carrier_bucket` while tail/small windows pay only their
    rounded size. Zero pad columns are inert, so G is bit-identical at
    any bucketing (pinned by the existing mixed-route pins)."""
    if rows >= block_variants:
        # Wider-than-block windows (only reachable through direct API
        # use — csr_windows caps at the block width) keep the exact
        # historical max(width, rows) behavior.
        return max(rows, 1)
    return min(_carrier_bucket(rows), block_variants)


def padded_carrier_matrix(
    window_idx: np.ndarray,
    lens: np.ndarray,
    sentinel: int,
    n_rows: Optional[int] = None,
    k_bucket: Optional[int] = None,
) -> np.ndarray:
    """One CSR window → a ``(n_rows, k_bucket)`` int32 carrier matrix.

    Row v holds variant v's carrier sample indices, right-padded with
    ``sentinel`` (any index ≥ the scatter target's row count — padded
    pairs are OOB and dropped by the kernel). ``n_rows`` pads the
    variant axis (tail windows, scan-chunk alignment); padded rows are
    all-sentinel and inert. ``k_bucket`` overrides the locally-derived
    power-of-two carrier bucket — the pod-sparse protocol passes the
    bucket of the GLOBAL max width so every process pads to one agreed
    geometry and the collective scatter executable caches per geometry
    across hosts, never per host. Pure vectorized numpy — this is host
    work on the ingest path, C-speed like the densify scatter it
    replaces.
    """
    lens = np.asarray(lens, dtype=np.int64)
    window_idx = np.asarray(window_idx, dtype=np.int64)
    rows = lens.size if n_rows is None else n_rows
    if rows < lens.size:
        raise ValueError(
            f"n_rows {rows} < window variant count {lens.size}"
        )
    k_local = int(lens.max()) if lens.size else 0
    if k_bucket is None:
        k_bucket = _carrier_bucket(k_local)
    elif k_bucket < k_local:
        raise ValueError(
            f"k_bucket {k_bucket} < window max carrier count {k_local}"
        )
    mat = np.full((rows, k_bucket), sentinel, dtype=np.int32)
    if window_idx.size:
        row_of = np.repeat(np.arange(lens.size, dtype=np.int64), lens)
        starts = np.zeros(lens.size, dtype=np.int64)
        np.cumsum(lens[:-1], out=starts[1:])
        pos = np.arange(window_idx.size, dtype=np.int64) - starts[row_of]
        mat[row_of, pos] = window_idx
    return mat


def scatter_pairs_chunked(g, row_idx, col_idx):
    """``g[row_idx[v,a], col_idx[v,b]] += 1`` for every (v, a, b) —
    out-of-bounds indices dropped.

    The ONE chunked-scan scatter body: the single-device kernel passes
    the carrier matrix as both operands; the mesh-tiled kernel passes
    tile-re-based row/column copies. Shared so a chunking or exactness
    change can never land in one copy and silently miss the other (the
    bit-identity contract the tests pin). Index arrays are
    ``(V_pad, k_bucket)`` with V_pad a multiple of the scan chunk; the
    scan bounds the broadcast update transient at
    ``chunk · k_bucket²``. Every update is an exact integer +1 in
    ``g.dtype`` — the same below-2^24 exactness contract as the dense
    accumulator.
    """
    one = jnp.asarray(1, g.dtype)
    shape = (
        row_idx.shape[0] // SCATTER_CHUNK_VARIANTS,
        SCATTER_CHUNK_VARIANTS,
        row_idx.shape[1],
    )

    def body(acc, chunk):
        ci, cj = chunk
        return (
            acc.at[ci[:, :, None], cj[:, None, :]].add(one, mode="drop"),
            None,
        )

    g, _ = jax.lax.scan(
        body, g, (row_idx.reshape(shape), col_idx.reshape(shape))
    )
    return g


@partial(jax.jit, donate_argnums=(0,), static_argnames=("path",))
def _scatter_accumulate_jit(g, idx, path="scan"):
    """``g[idx[v,a], idx[v,b]] += 1`` for every (v, a, b) — OOB dropped.

    ``path`` is the pre-resolved scatter implementation
    (:func:`spark_examples_tpu.ops.scatter_kernel.resolve_scatter_path`):
    the chunked-scan body, or the Pallas one-hot-count kernel
    (compiled / interpreter mode) — bit-identical either way.
    """
    if path == "scan":
        return scatter_pairs_chunked(g, idx, idx)
    from spark_examples_tpu.ops.scatter_kernel import scatter_pairs_kernel

    return scatter_pairs_kernel(g, idx, idx, interpret=path == "interpret")


def _pad_rows_for_scan(rows: int) -> int:
    """Variant-axis padding so the scan chunking divides evenly."""
    from spark_examples_tpu.arrays.blocks import round_up_multiple

    return round_up_multiple(max(rows, 1), SCATTER_CHUNK_VARIANTS)


def sparse_gramian_accumulate(g, window_idx, lens, scatter_path=None):
    """One sparse accumulation step: scatter a CSR window into G.

    ``g`` is the ``(N, N)`` device accumulator (donated — updates in
    place in device memory); the window is host CSR ``(indices, lens)``.
    Returns the updated G. Bit-identical to densifying the window and
    running ``gramian_accumulate`` (pinned by tests). ``scatter_path``
    pre-resolves the scan-vs-Pallas-kernel choice for streams that
    dispatch many windows (resolved per call here when ``None``).
    """
    from spark_examples_tpu.ops.scatter_kernel import resolve_scatter_path

    if scatter_path is None:
        scatter_path = resolve_scatter_path(g.shape, g.dtype)
    idx = padded_carrier_matrix(
        window_idx,
        lens,
        sentinel=g.shape[0],
        n_rows=_pad_rows_for_scan(np.asarray(lens).size),
    )
    return _scatter_accumulate_jit(g, idx, path=scatter_path)


def _note_window(route: str, nnz: int, count: int = 1) -> None:
    """Per-window telemetry shared by the single-device and mesh
    accumulators (one registration site per metric, GL003). ``count``
    is the number of CSR windows this accumulation step carried — the
    pod protocol's coalesced gangs fold several windows into one step,
    and a pod step fed purely by inert padding (this process drained,
    peers still live) carries zero."""
    from spark_examples_tpu import obs

    reg = obs.get_registry()
    reg.counter(
        "sparse_gramian_windows_total",
        "CSR windows accumulated by the sparse-aware Gramian engine",
    ).labels(route=route).inc(count)
    reg.counter(
        "sparse_gramian_nnz_total",
        "Genotype carriers (nonzeros) accumulated by the sparse engine",
    ).inc(nnz)


def _note_pod_gang(n_windows: int) -> None:
    """Pod-sparse coalescing telemetry: how many local CSR windows one
    protocol step carried, labeled by whether they rode a multi-window
    gang (``mode="gang"``) or a solo step (``mode="solo"``) — the label
    set ``validate_trace._LABELED_COUNTERS`` enforces (GL003). One
    registration site; inert (zero-window) steps are not noted."""
    if n_windows <= 0:
        return
    from spark_examples_tpu import obs

    obs.get_registry().counter(
        "sparse_pod_coalesced_windows_total",
        "Local CSR windows entering pod-sparse protocol steps, by "
        "gang/solo coalescing outcome",
    ).labels(mode="gang" if n_windows > 1 else "solo").inc(n_windows)


def _note_pod_sync(outcome: str) -> None:
    """Per-step pod-sparse sync telemetry (the carrier-allgather
    protocol in ``parallel/sharded._synced_carrier_stream``): one
    registration site, outcome ∈ {synced, drained, producer-error,
    route-divergence, dtype-divergence} — the label set
    ``validate_trace._LABELED_COUNTERS`` enforces (GL003)."""
    from spark_examples_tpu import obs

    obs.get_registry().counter(
        "sparse_pod_sync_total",
        "Pod-sparse per-window sync steps (header + carrier allgather) "
        "by outcome",
    ).labels(outcome=outcome).inc()


def sparse_gramian_blockwise(
    windows: Iterable[Tuple[np.ndarray, np.ndarray]],
    n_samples: int,
    accum_dtype=jnp.float32,
    density_threshold: float = DEFAULT_SPARSE_DENSITY_THRESHOLD,
    block_variants: Optional[int] = None,
    device=None,
):
    """Stream CSR windows into a single-device G, routing per density.

    ``windows`` yields ``(indices, lens)`` pairs (``csr_windows``
    output). Sparse windows scatter straight from CSR; dense windows
    take the historical densify → bit-pack → MXU route (padded to
    ``block_variants`` so the packed executable shape stays stable).
    The mix is bit-identical to the pure dense path — both routes add
    exact integer counts.
    """
    from spark_examples_tpu import obs
    from spark_examples_tpu.arrays.blocks import (
        DEFAULT_BLOCK_VARIANTS,
        _check_indices,
        _densify_window,
    )
    from spark_examples_tpu.ops.gramian import (
        gramian_accumulate_packed,
        pack_indicator_block,
    )

    from spark_examples_tpu.ops.scatter_kernel import resolve_scatter_path

    width = block_variants or DEFAULT_BLOCK_VARIANTS
    g = jnp.zeros((n_samples, n_samples), dtype=accum_dtype)
    if device is not None:
        g = jax.device_put(g, device)
    # One scan-vs-kernel resolution per stream (outside any trace), so
    # the whole accumulation rides one executable family.
    scatter_path = resolve_scatter_path(
        (n_samples, n_samples), np.dtype(accum_dtype)
    )
    with obs.span("gramian.sparse.accumulate", n=n_samples):
        for window_idx, lens in windows:
            lens = np.asarray(lens)
            _check_indices(np.asarray(window_idx), n_samples)
            route = window_route(lens, n_samples, density_threshold)
            nnz = int(lens.sum())
            with obs.span(
                "gramian.sparse.window",
                route=route,
                nnz=nnz,
                variants=int(lens.size),
            ):
                if route == "scatter":
                    g = sparse_gramian_accumulate(
                        g, window_idx, lens, scatter_path=scatter_path
                    )
                else:
                    dense_width = dense_panel_width(
                        int(lens.size), width
                    )
                    xp = pack_indicator_block(
                        _densify_window(
                            window_idx, lens, n_samples, dense_width
                        )
                    )
                    g = gramian_accumulate_packed(g, xp)
            _note_window(route, nnz)
    return g
