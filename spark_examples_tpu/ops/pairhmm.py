"""Batched log-space PairHMM forward kernel — the read-level workload.

The reference's reads side (``SearchReadsExample.scala``) never got past
per-base counting; the read-level kernel every production variant caller
actually burns cycles on is the PairHMM forward pass: P(read | haplotype)
under a three-state (match / insertion / deletion) hidden Markov model,
one banded dynamic program per read×haplotype pair, millions of pairs
per sample (*Endeavor: Efficient PairHMM*, arxiv 2606.25738; the GPU
pipeline study arxiv 2509.09058 measures it at 30-70% of HaplotypeCaller
wall-clock). The TPU-native formulation here:

- **anti-diagonal ``lax.scan``**: cell (i, j) of the DP matrix depends
  on (i-1, j-1), (i-1, j) and (i, j-1) — all on the two previous
  anti-diagonals, so every cell of diagonal d computes in parallel on
  the VPU and the scan walks d = 1 .. R+H with a static trip count.
  Three carried diagonals per state (current-1, current-2), one fused
  masked update per step — no (R+1)×(H+1) matrix is ever materialized.
- **batched pairs**: thousands of pairs stack on a leading batch axis;
  every op in the recurrence is elementwise along the batch, so each
  pair's result is bit-identical whatever tile it rides in (pinned by
  test — the completion-order feed upstream reorders freely).
- **log-space f32** with a finite ``PAIRHMM_NEG_INF`` sentinel
  (``-inf`` breeds NaNs through masked ``where`` gradients and
  ``0 * inf``; a finite floor keeps every ``logaddexp`` well-defined
  while exp(sentinel - max) underflows to exactly 0).
- **per-pair length masks**: reads and haplotypes bucket to power-of-two
  lengths (:func:`pairhmm_bucket` — the GL012-registered discipline that
  bounds executable count at O(log R · log H) like the sparse engine's
  carrier buckets); cells beyond a pair's true (r, h) are masked to the
  sentinel and padded batch slots (r = 0) report the sentinel.

Model (GATK LoglessPairHMM conventions, the de-facto contract every
hardware PairHMM reproduces):

- emission at (i, j): ``1 - eps_i`` when read base i matches haplotype
  base j, ``eps_i / 3`` otherwise, with ``eps_i = 10^(-Q_i / 10)`` from
  the read's per-base quality (code 4 = N never matches);
- transitions from two phred-scaled knobs, gap-open ``go`` and
  gap-extend ``ge``: M→M ``1 - 2·10^(-go/10)``, M→{I,D} ``10^(-go/10)``,
  {I,D} self ``10^(-ge/10)``, {I,D}→M ``1 - 10^(-ge/10)``;
- free alignment start: row 0 of the deletion matrix holds ``1/h``
  (haplotype length h), so the likelihood sums over all start offsets;
- result: ``log Σ_j (M[r, j] + I[r, j])`` — natural log, a genuine
  log P(read | haplotype).

The scalar float64 numpy golden (:func:`pairhmm_forward_ref`) is the
parity oracle: the batched f32 kernel must match it within the
documented tolerances (:data:`PAIRHMM_FORWARD_RTOL` /
:data:`PAIRHMM_FORWARD_ATOL`) across length buckets, masked pads, and
shuffled pair orders — the contract ``tests/test_pairhmm.py`` pins and
``tests_tpu/test_pairhmm_tpu.py`` certifies on hardware.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "DEFAULT_GAP_EXT_PHRED",
    "DEFAULT_GAP_OPEN_PHRED",
    "MIN_GAP_OPEN_PHRED",
    "PAIRHMM_FORWARD_ATOL",
    "PAIRHMM_FORWARD_RTOL",
    "PAIRHMM_NEG_INF",
    "pairhmm_bucket",
    "pairhmm_forward_batch",
    "pairhmm_forward_ref",
]

# Finite log-space floor: far below any reachable log-likelihood
# (a 10 kb read of all-mismatch Q60 bases sits near -1.4e5), yet
# exp(PAIRHMM_NEG_INF - anything) is exactly 0.0 in f32 — masked cells
# contribute nothing and never produce inf - inf NaNs.
PAIRHMM_NEG_INF = -1.0e30

# GATK defaults: gap-open Q45 (~3.2e-5), gap-extend Q10 (0.1).
DEFAULT_GAP_OPEN_PHRED = 45.0
DEFAULT_GAP_EXT_PHRED = 10.0

# Hard floor for the gap-open penalty: at or below 10·log10(2) ≈ 3.01
# the match self-transition 1 - 2·10^(-go/10) is non-positive, its log
# is NaN, and every likelihood in the tile is NaN. Validated loudly at
# the driver boundary, never discovered as a sea of NaNs.
MIN_GAP_OPEN_PHRED = float(10.0 * np.log10(2.0))

# f32-vs-f64 parity contract for the batched forward pass. Error grows
# with the R+H logaddexp chain length; at read/hap lengths into the
# low thousands the observed max deviation stays under 1e-3 absolute on
# log-likelihoods of magnitude 10-10^3, so these bounds carry an order
# of magnitude of margin. tests/test_pairhmm.py asserts through them.
PAIRHMM_FORWARD_RTOL = 1e-4
PAIRHMM_FORWARD_ATOL = 2e-2

_MIN_PAIRHMM_BUCKET = 8

_LN10_OVER_10 = float(np.log(10.0) / 10.0)
_LN3 = float(np.log(3.0))


def pairhmm_bucket(n: int, floor: int = _MIN_PAIRHMM_BUCKET) -> int:
    """Round a read/haplotype length (or tile batch count) up to a
    power of two (min ``floor``): bucket dimensions are jit operand
    shapes, so bucketing bounds the executable count at O(log R ·
    log H · log B) — the same argument as the sparse engine's
    ``_carrier_bucket``, registered with graftlint's GL012
    retrace-discipline rule like it."""
    bucket = max(1, floor)
    while bucket < n:
        bucket *= 2
    return bucket


def _shift1(x, neg):
    """``x[:, i-1]`` along the read axis with the sentinel at i = 0 —
    the previous-diagonal read-index offset of the recurrence."""
    return jnp.concatenate(
        [jnp.full((x.shape[0], 1), neg, x.dtype), x[:, :-1]], axis=1
    )


@jax.jit
def pairhmm_forward_batch(
    read_codes,
    read_quals,
    read_lens,
    hap_codes,
    hap_lens,
    gap_open_phred,
    gap_ext_phred,
):
    """Log P(read | haplotype) for a tile of pairs, in one scan.

    Args:
      read_codes: (B, R) int8 base codes (0-3 = ACGT, 4 = N; entries
        past each pair's ``read_lens`` are ignored).
      read_quals: (B, R) per-base phred qualities (int or float).
      read_lens: (B,) true read lengths (0 = padded batch slot).
      hap_codes: (B, H) int8 haplotype base codes.
      hap_lens: (B,) true haplotype lengths.
      gap_open_phred / gap_ext_phred: scalar phred-scaled gap penalties.

    Returns:
      (B,) float32 natural-log likelihoods; padded slots (read_lens or
      hap_lens 0) report :data:`PAIRHMM_NEG_INF`. Every op along the
      batch axis is elementwise, so a pair's value is bit-identical in
      any tile composition or order.

    All geometry derives from the operand shapes (no static args): one
    executable per (B, R, H) bucket triple, O(log³) total under
    :func:`pairhmm_bucket`.
    """
    f32 = jnp.float32
    b, r_bucket = read_codes.shape
    h_bucket = hap_codes.shape[1]
    neg = jnp.asarray(PAIRHMM_NEG_INF, f32)
    ln10_10 = jnp.asarray(_LN10_OVER_10, f32)

    # Per-base emission log-probs, shifted so index i reads base i-1.
    log_eps = -read_quals.astype(f32) * ln10_10  # (B, R)
    lp_match = jnp.log1p(-jnp.exp(log_eps))
    lp_mis = log_eps - jnp.asarray(_LN3, f32)
    pad1 = jnp.full((b, 1), neg, f32)
    lpm = jnp.concatenate([pad1, lp_match], axis=1)  # (B, R+1)
    lpx = jnp.concatenate([pad1, lp_mis], axis=1)
    rc = jnp.concatenate(
        [jnp.full((b, 1), 5, read_codes.dtype), read_codes], axis=1
    )

    # Transition log-probs (scalars).
    go = jnp.asarray(gap_open_phred, f32)
    ge = jnp.asarray(gap_ext_phred, f32)
    eps_go = jnp.exp(-go * ln10_10)
    t_mm = jnp.log1p(-jnp.asarray(2.0, f32) * eps_go)
    t_open = -go * ln10_10  # log eps_go
    t_ext = -ge * ln10_10  # log eps_ge
    t_close = jnp.log1p(-jnp.exp(t_ext))

    r_len = read_lens.astype(jnp.int32)[:, None]  # (B, 1)
    h_len = hap_lens.astype(jnp.int32)[:, None]
    log_init = jnp.where(
        h_len > 0,
        -jnp.log(jnp.maximum(h_len, 1).astype(f32)),
        neg,
    )

    # Reversed haplotype padded on both sides so diagonal d's base at
    # read index i — hap[d-1-i] — is one dynamic slice of length R+1:
    # rev[H-1-(d-1-i)] = rev[H-d+i], padded left by P = R+1 keeps every
    # slice start P+H-d in bounds for d in [1, R+H].
    sentinel_codes = jnp.full((b, r_bucket + 1), 4, hap_codes.dtype)
    pad_rev = jnp.concatenate(
        [sentinel_codes, hap_codes[:, ::-1], sentinel_codes], axis=1
    )

    i_idx = jnp.arange(r_bucket + 1, dtype=jnp.int32)[None, :]  # (1, R+1)
    diag0 = jnp.full((b, r_bucket + 1), neg, f32)
    init = (
        diag0,  # M on diagonal d-1
        diag0,  # I on diagonal d-1
        jnp.where(i_idx == 0, log_init, neg),  # D: cell (0, 0) boundary
        diag0,  # M on diagonal d-2
        diag0,  # I on diagonal d-2
        diag0,  # D on diagonal d-2
        jnp.full((b,), neg, f32),  # running final-row logsumexp
    )

    def step(carry, d):
        m1, i1, d1, m2, i2, d2, acc = carry
        j = d - i_idx  # column index of cell (i, j) on diagonal d
        start = (r_bucket + 1) + h_bucket - d
        hap_at = jax.lax.dynamic_slice_in_dim(
            pad_rev, start, r_bucket + 1, axis=1
        )
        match = (hap_at == rc) & (rc < 4) & (hap_at < 4)
        prior = jnp.where(match, lpm, lpx)
        m_new = prior + jnp.logaddexp(
            t_mm + _shift1(m2, neg),
            jnp.logaddexp(
                t_close + _shift1(i2, neg), t_close + _shift1(d2, neg)
            ),
        )
        i_new = jnp.logaddexp(
            t_open + _shift1(m1, neg), t_ext + _shift1(i1, neg)
        )
        d_new = jnp.logaddexp(t_open + m1, t_ext + d1)
        valid = (i_idx >= 1) & (i_idx <= r_len) & (j >= 1) & (j <= h_len)
        m_new = jnp.where(valid, m_new, neg)
        i_new = jnp.where(valid, i_new, neg)
        d_new = jnp.where(valid, d_new, neg)
        # Boundary row i = 0 (cell (0, d)): the free-start deletion
        # mass, live while the column is inside the haplotype.
        d_new = jnp.where((i_idx == 0) & (j <= h_len), log_init, d_new)
        # Final-row readout: cell (r, d - r) when it lands in-matrix.
        m_r = jnp.take_along_axis(m_new, r_len, axis=1)[:, 0]
        i_r = jnp.take_along_axis(i_new, r_len, axis=1)[:, 0]
        j_r = d - r_len[:, 0]
        in_row = (
            (r_len[:, 0] >= 1) & (j_r >= 1) & (j_r <= h_len[:, 0])
        )
        contrib = jnp.where(in_row, jnp.logaddexp(m_r, i_r), neg)
        acc = jnp.logaddexp(acc, contrib)
        return (m_new, i_new, d_new, m1, i1, d1, acc), None

    carry, _ = jax.lax.scan(
        step,
        init,
        jnp.arange(1, r_bucket + h_bucket + 1, dtype=jnp.int32),
    )
    return carry[-1]


def pairhmm_forward_ref(
    read_codes,
    read_quals,
    hap_codes,
    gap_open_phred: float = DEFAULT_GAP_OPEN_PHRED,
    gap_ext_phred: float = DEFAULT_GAP_EXT_PHRED,
) -> float:
    """Scalar float64 golden: the full (r+1)×(h+1) log-space DP.

    The direct transcription of the model in the module docstring — no
    diagonals, no masks, no buckets — against which the batched kernel
    holds tolerance parity. Returns ``-inf`` for an empty read or
    haplotype (the kernel's padded slots report the finite sentinel).
    """
    read_codes = np.asarray(read_codes, dtype=np.int64)
    hap_codes = np.asarray(hap_codes, dtype=np.int64)
    quals = np.asarray(read_quals, dtype=np.float64)
    r, h = read_codes.size, hap_codes.size
    if r == 0 or h == 0:
        return float("-inf")
    eps = np.power(10.0, -quals / 10.0)
    lp_match = np.log1p(-eps)
    lp_mis = np.log(eps / 3.0)
    eps_go = 10.0 ** (-float(gap_open_phred) / 10.0)
    eps_ge = 10.0 ** (-float(gap_ext_phred) / 10.0)
    t_mm = np.log1p(-2.0 * eps_go)
    t_open = np.log(eps_go)
    t_ext = np.log(eps_ge)
    t_close = np.log1p(-eps_ge)
    neg = -np.inf
    m = np.full((r + 1, h + 1), neg)
    ins = np.full((r + 1, h + 1), neg)
    dele = np.full((r + 1, h + 1), neg)
    dele[0, :] = -np.log(float(h))
    for i in range(1, r + 1):
        for j in range(1, h + 1):
            hit = (
                read_codes[i - 1] == hap_codes[j - 1]
                and read_codes[i - 1] < 4
                and hap_codes[j - 1] < 4
            )
            prior = lp_match[i - 1] if hit else lp_mis[i - 1]
            m[i, j] = prior + np.logaddexp(
                t_mm + m[i - 1, j - 1],
                np.logaddexp(
                    t_close + ins[i - 1, j - 1],
                    t_close + dele[i - 1, j - 1],
                ),
            )
            ins[i, j] = np.logaddexp(
                t_open + m[i - 1, j], t_ext + ins[i - 1, j]
            )
            dele[i, j] = np.logaddexp(
                t_open + m[i, j - 1], t_ext + dele[i, j - 1]
            )
    row = np.logaddexp(m[r, 1:], ins[r, 1:])
    peak = row.max()
    return float(peak + np.log(np.exp(row - peak).sum()))
