"""Sample co-occurrence Gramian as MXU matmuls.

Semantics (reference ``VariantsPca.scala:170-191``): for each variant, every
unordered pair of samples that both carry a non-reference allele contributes
+1 to ``G[i, j]`` (and the diagonal counts each sample against itself). With
the per-variant sample-index lists densified to a 0/1 indicator block
``X ∈ {0,1}^(N_samples × V_variants)`` this is exactly ``G = X @ X.T`` — the
O(k²)-per-variant scalar loop of the reference becomes one batched matmul.

Counts are integers, so an f32 matmul of 0/1 operands is *exact* as long as
no entry of G exceeds 2^24 (16.7M co-occurring variants per sample pair) —
far beyond the all-autosomes 1000 Genomes scale (~40M variants total, but a
single pair co-occurring at every variant would still need f64/int paths;
``gramian_blockwise`` therefore accumulates into an f64-safe int32/float32
choice via ``accum_dtype``).

TPU notes: X is stored int8 host-side (HBM-friendly). By default the
per-block product rides the **integer MXU**: int8×int8→int32, then the exact
int32 counts are cast into the accumulator dtype (float32 by default, exact
below 2^24 total co-occurrences per pair). Measured on a real TPU v5 lite at
the bench shape (N=2504, V=65536, end-to-end blockwise stream including
host→device transfer; ``tpu_capture_r03/mode_probe.jsonl``):

    int8 einsum 0.197s | f32 einsum 0.353s | bf16 0.312s |
    pallas dense 2.75s | pallas sym 2.19s

so int8 is 1.8× over f32 and both hand-written Pallas kernels lost to the
XLA einsum by ~10× end-to-end — the Pallas path was deleted on that
evidence (they remain in git history; the hardware bit-exactness suite had
them at parity numerically). ``SPARK_EXAMPLES_TPU_GRAMIAN=f32`` forces the
matmul itself into the accumulator dtype (escape hatch; observably
identical results either way — both paths are exact integer counts).
"""

from __future__ import annotations

import os
from functools import partial
from typing import Iterable

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "gang_gramian_blockwise",
    "gramian",
    "gramian_accumulate",
    "gramian_accumulate_packed",
    "gramian_blockwise",
    "mxu_cross_product",
    "mxu_cross_product_pair",
    "pack_indicator_block",
    "resolve_gramian_compute_dtype",
    "unpack_indicator_block",
]


def resolve_gramian_compute_dtype(x_dtype, out_dtype, compute_dtype=None):
    """Pick the MXU dtype for one Gramian call — OUTSIDE any jit trace.

    Every public entry point resolves the mode here before entering its
    jitted body, so ``SPARK_EXAMPLES_TPU_GRAMIAN`` is consulted (and
    validated) on every call rather than frozen into the first trace's
    cached executable. Policy: explicit ``compute_dtype`` wins; env
    ``f32`` forces the matmul into ``out_dtype``; env ``int8`` forces the
    integer MXU; default rides the integer MXU whenever X is stored int8.
    """
    if compute_dtype is not None:
        return compute_dtype
    forced = os.environ.get("SPARK_EXAMPLES_TPU_GRAMIAN", "")
    if forced not in ("", "auto", "int8", "f32"):
        raise ValueError(
            f"SPARK_EXAMPLES_TPU_GRAMIAN={forced!r}: expected 'auto', "
            "'int8', or 'f32'"
        )
    if forced == "f32":
        return out_dtype
    if forced == "int8" or x_dtype == jnp.int8:
        return jnp.int8
    return out_dtype


def mxu_cross_product(x, out_dtype, compute_dtype=None):
    """``X @ X.T`` in the fastest exact dtype path for 0/1 indicators.

    The single mode-policy seam shared by every Gramian entry point
    (single-device and sharded): int8-stored blocks ride the integer MXU
    (int8×int8→int32, 1.8× over f32 on TPU v5e — module docstring table)
    and the exact int32 product is cast to ``out_dtype``; anything else
    computes directly in ``out_dtype``. NOTE: when called inside a jit /
    shard_map trace with ``compute_dtype=None``, the env escape hatch is
    resolved at trace time — callers that want per-call env semantics
    must resolve via :func:`resolve_gramian_compute_dtype` outside the
    trace (all public entry points here and in ``parallel/sharded`` do).
    """
    return mxu_cross_product_pair(x, x, out_dtype, compute_dtype)


def mxu_cross_product_pair(a, b, out_dtype, compute_dtype=None):
    """``A @ B.T`` under the Gramian exact-dtype policy — the
    cross-tile form the pod-sparse dense step uses (each device
    multiplies its tile's ROW slice of X against its COLUMN slice).
    :func:`mxu_cross_product` is the ``a is b`` special case and
    delegates here, so the integer-MXU routing and the exactness
    argument live in exactly ONE body."""
    compute_dtype = resolve_gramian_compute_dtype(
        a.dtype, out_dtype, compute_dtype
    )
    af, bf = a.astype(compute_dtype), b.astype(compute_dtype)
    if compute_dtype == jnp.int8:
        prod = jnp.einsum(
            "nv,mv->nm", af, bf, preferred_element_type=jnp.int32
        )
        return prod.astype(out_dtype)
    return jnp.einsum("nv,mv->nm", af, bf, preferred_element_type=out_dtype)


@partial(jax.jit, static_argnames=("compute_dtype", "accum_dtype"))
def _gramian_jit(x, compute_dtype, accum_dtype):
    return mxu_cross_product(x, accum_dtype, compute_dtype)


def gramian(x, compute_dtype=None, accum_dtype=jnp.float32):
    """``G = X @ X.T`` for a 0/1 genotype-indicator block.

    Args:
      x: ``(n_samples, n_variants)`` array, any integer/float dtype with 0/1
        values (int8 preferred for storage).
      compute_dtype: dtype the matmul runs in on the MXU; ``None`` picks the
        measured-fastest exact path (int8 for int8 storage, modulo the env
        escape hatch).
      accum_dtype: dtype of the returned Gramian.

    Returns:
      ``(n_samples, n_samples)`` symmetric co-occurrence matrix.
    """
    compute_dtype = resolve_gramian_compute_dtype(
        x.dtype, accum_dtype, compute_dtype
    )
    from spark_examples_tpu.obs.xla import record_compiled

    record_compiled("gramian", _gramian_jit, x, compute_dtype, accum_dtype)
    return _gramian_jit(x, compute_dtype, accum_dtype)


@partial(jax.jit, static_argnames=("compute_dtype",), donate_argnums=(0,))
def _gramian_accumulate_jit(g, x_block, compute_dtype):
    return g + mxu_cross_product(x_block, g.dtype, compute_dtype)


def gramian_accumulate(g, x_block, compute_dtype=None):
    """One blockwise-accumulation step: ``G += X_blk @ X_blk.T``.

    This is the variant-axis streaming primitive (the reference's
    ``getSimilarityMatrixStream`` memory/shuffle tradeoff,
    ``VariantsPca.scala:248-279``, re-done TPU-style): the variant axis is
    unbounded while G stays fixed at N×N on device. ``g`` is donated so the
    accumulator updates in place in HBM.
    """
    compute_dtype = resolve_gramian_compute_dtype(
        x_block.dtype, g.dtype, compute_dtype
    )
    return _gramian_accumulate_jit(g, x_block, compute_dtype)


def pack_indicator_block(x_block: np.ndarray) -> np.ndarray:
    """Host-side bit-pack of a 0/1 indicator block: (N, V) → (N, ⌈V/8⌉).

    The variant axis is transfer-bound through any host→device link (and
    especially the axon tunnel); 0/1 indicators waste 7 of every 8 bits
    of an int8 block. ``np.packbits`` is C-speed and the pack overlaps
    the previous block's device matmul in the prefetch pipeline.

    PRECONDITION: values must be 0/1 indicators. Packing collapses any
    nonzero value to 1 (``astype(bool)``), which would silently corrupt a
    dosage-valued block (0/1/2) into a wrong Gramian. A strided subsample
    (≤64Ki elements, so the check never competes with packbits itself at
    the ~160 MB bench block size) is validated on every call; it cannot
    catch every stray value, so block producers own the full invariant.
    """
    x_block = np.asarray(x_block)
    if x_block.size:
        flat = x_block.reshape(-1)
        step = max(1, flat.shape[0] // 65536)
        sample = flat[::step]
        # Exact-0/1 check (not a range check): a fractional dosage like
        # 0.5 sits inside [0, 1] but still collapses to 1 under
        # astype(bool) — compare against the round-trip instead.
        if not np.array_equal(sample, sample.astype(bool)):
            bad_lo, bad_hi = sample.min(), sample.max()
            raise ValueError(
                "pack_indicator_block requires exact 0/1 indicator values; "
                f"got values in [{bad_lo}, {bad_hi}] (dosage-valued blocks "
                "must use the unpacked path)"
            )
    return np.packbits(x_block.astype(bool), axis=1)


def unpack_indicator_block(x_packed, n_bits: int):
    """Device-side unpack: (N, ⌈V/8⌉) uint8 → (N, n_bits) int8 0/1.

    A broadcasted shift-and-mask XLA fuses into the consumer; the
    transient (N, V) int8 is the same HBM footprint the unpacked path
    would have transferred anyway.
    """
    shifts = jnp.arange(7, -1, -1, dtype=jnp.uint8)
    bits = (x_packed[:, :, None] >> shifts) & jnp.uint8(1)
    return bits.reshape(x_packed.shape[0], -1)[:, :n_bits].astype(jnp.int8)


@partial(
    jax.jit,
    static_argnames=("n_bits", "compute_dtype"),
    donate_argnums=(0,),
)
def _gramian_accumulate_packed_jit(g, x_packed, n_bits, compute_dtype):
    x = unpack_indicator_block(x_packed, n_bits)
    return g + mxu_cross_product(x, g.dtype, compute_dtype)


def gramian_accumulate_packed(g, x_packed, n_bits=None, compute_dtype=None):
    """``G += X_blk @ X_blk.T`` from a bit-packed block (8× less transfer).

    ``x_packed`` is :func:`pack_indicator_block` output (host or device);
    ``n_bits`` is the true variant count V of the block (default: all
    8·⌈V/8⌉ columns — the pad bits packbits appends are zero and inert in
    the Gramian, so the default is safe). Bit-identical to the unpacked
    path; measured on-chip before being offered (PERFORMANCE.md).
    """
    if n_bits is None:
        n_bits = 8 * x_packed.shape[1]
    compute_dtype = resolve_gramian_compute_dtype(
        jnp.int8, g.dtype, compute_dtype
    )
    return _gramian_accumulate_packed_jit(g, x_packed, n_bits, compute_dtype)


@partial(
    jax.jit,
    static_argnames=("compute_dtype",),
    donate_argnums=(0,),
)
def _gang_accumulate_jit(g, x_stack, compute_dtype):
    """One gang step: ``G[b] += X[b] @ X[b].T`` for every cohort b —
    the per-cohort Gramian step vmapped over the leading batch axis, so
    B small-cohort accumulations ride ONE dispatch and one executable
    (the MXU analogue of request coalescing)."""
    return g + jax.vmap(
        lambda xb: mxu_cross_product(xb, g.dtype, compute_dtype)
    )(x_stack)


def gang_gramian_blockwise(
    windows: Iterable,
    remaps,
    n_max: int,
    block_variants: int = 8192,
    accum_dtype=jnp.float32,
    compute_dtype=None,
):
    """Batched Gramians for B cohorts from ONE full-frame window stream.

    ``windows`` yields full-frame ``(indices, lens)`` CSR windows (the
    ``csr_windows``/``windows_from_calls`` shape); ``remaps`` is one
    int array per cohort mapping full-frame sample index → that
    cohort's dense index (< 0 drops the carrier). Every window is
    scattered into one ``(B, n_max, width)`` int8 stack (cohorts
    shorter than ``n_max`` zero-pad — inert rows) and accumulated by
    the vmapped batch step: ONE jit cache entry for the whole gang,
    device round-trips amortized B-fold. Each ``G[b]``'s top-left
    ``(n_b, n_b)`` corner is bit-identical to that cohort's serial
    accumulation — exact integer counts, any composition (pinned by
    tests).

    Returns the host ``(B, n_max, n_max)`` f32 stack (callers slice
    per-cohort corners).
    """
    batch = len(remaps)
    if batch == 0:
        raise ValueError("gang_gramian_blockwise needs >= 1 cohort")
    remaps = [np.asarray(r, dtype=np.int64) for r in remaps]
    g = jnp.zeros((batch, n_max, n_max), dtype=accum_dtype)
    compute_dtype = resolve_gramian_compute_dtype(
        jnp.int8, accum_dtype, compute_dtype
    )
    for window_idx, lens in windows:
        window_idx = np.asarray(window_idx, dtype=np.int64)
        lens = np.asarray(lens, dtype=np.int64)
        # Fixed width = the block width: every full-size window hits
        # the same executable; only the tail window pays a second one.
        width = max(int(lens.size), 1)
        if width < block_variants:
            width = block_variants
        cols = np.repeat(np.arange(lens.size, dtype=np.int64), lens)
        stack = np.zeros((batch, n_max, width), dtype=np.int8)
        for b, remap in enumerate(remaps):
            mapped = remap[window_idx]
            keep = mapped >= 0
            stack[b][mapped[keep], cols[keep]] = 1
        g = _gang_accumulate_jit(g, stack, compute_dtype)
    return np.asarray(g)


def gramian_blockwise(
    blocks: Iterable[np.ndarray],
    n_samples: int,
    accum_dtype=jnp.float32,
    compute_dtype=None,
    device=None,
    packed: bool = False,
    prepacked: bool = False,
    prefetch_depth: int = 2,
):
    """Stream variant blocks through ``G += X_blk @ X_blk.T`` on device.

    Host generator → device accumulation; each block is transferred while the
    previous block's matmul runs (JAX dispatch is async, so transfer/compute
    overlap comes for free as long as blocks are pre-staged with
    ``jax.device_put``).

    Args:
      blocks: iterable of host ``(n_samples, v_blk)`` 0/1 arrays (ragged
        ``v_blk`` allowed; recompilation is avoided by padding upstream in
        :mod:`spark_examples_tpu.arrays.blocks`).
      n_samples: N — fixed by the callset index before any variant is read
        (reference ``VariantsCommon.scala:38-50``).
      prepacked: with ``packed=True``, the blocks are ALREADY
        ``pack_indicator_block`` output (uint8 bytes) — skip the host
        pack (callers that keep a packed cohort resident, and the
        native ingest engine's direct-packed block production).
      prefetch_depth: device-feed staging depth (``--prefetch-depth``):
        how many transferred blocks the double-buffered prefetch keeps
        ahead of the accumulating matmul.

    Returns:
      ``(N, N)`` device Gramian.
    """
    from spark_examples_tpu import obs
    from spark_examples_tpu.arrays.feed import device_prefetch
    from spark_examples_tpu.obs.xla import record_compiled

    g = jnp.zeros((n_samples, n_samples), dtype=accum_dtype)
    if device is not None:
        g = jax.device_put(g, device)
    if packed:
        # Pack on the host inside the prefetch generator so packing one
        # block overlaps the previous block's transfer+matmul. No width
        # side-channel needed: packbits pad bits unpack to zero columns,
        # which are inert in X @ X.T.
        def packed_stream():
            for xb in blocks:
                if prepacked:
                    yield xb
                else:
                    # Span closed BEFORE the yield: it must time the
                    # pack, not the consumer's turn of the generator.
                    with obs.span("ingest.pack"):
                        xp = pack_indicator_block(xb)
                    yield xp

        with obs.span("gramian_blockwise", packed=True):
            for i, xp in enumerate(
                device_prefetch(
                    packed_stream(), depth=prefetch_depth, device=device
                )
            ):
                if i == 0:
                    record_compiled(
                        "gramian_accumulate_packed",
                        _gramian_accumulate_packed_jit,
                        g,
                        xp,
                        8 * xp.shape[1],
                        resolve_gramian_compute_dtype(
                            jnp.int8, g.dtype, compute_dtype
                        ),
                    )
                # One span per accumulation DISPATCH (async; ~µs): its
                # start is the cold-stream acceptance anchor — the
                # first accumulate must begin while later shards are
                # still inside their ingest.fetch spans.
                with obs.span("gramian.accumulate", block=i):
                    g = gramian_accumulate_packed(
                        g, xp, compute_dtype=compute_dtype
                    )
        return g
    with obs.span("gramian_blockwise", packed=False):
        for i, xb in enumerate(
            device_prefetch(blocks, depth=prefetch_depth, device=device)
        ):
            if i == 0:
                record_compiled(
                    "gramian_accumulate",
                    _gramian_accumulate_jit,
                    g,
                    xb,
                    resolve_gramian_compute_dtype(
                        xb.dtype, g.dtype, compute_dtype
                    ),
                )
            with obs.span("gramian.accumulate", block=i):
                g = gramian_accumulate(g, xb, compute_dtype=compute_dtype)
    return g
