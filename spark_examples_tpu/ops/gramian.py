"""Sample co-occurrence Gramian as MXU matmuls.

Semantics (reference ``VariantsPca.scala:170-191``): for each variant, every
unordered pair of samples that both carry a non-reference allele contributes
+1 to ``G[i, j]`` (and the diagonal counts each sample against itself). With
the per-variant sample-index lists densified to a 0/1 indicator block
``X ∈ {0,1}^(N_samples × V_variants)`` this is exactly ``G = X @ X.T`` — the
O(k²)-per-variant scalar loop of the reference becomes one batched matmul.

Counts are integers, so an f32 matmul of 0/1 operands is *exact* as long as
no entry of G exceeds 2^24 (16.7M co-occurring variants per sample pair) —
far beyond the all-autosomes 1000 Genomes scale (~40M variants total, but a
single pair co-occurring at every variant would still need f64/int paths;
``gramian_blockwise`` therefore accumulates into an f64-safe int32/float32
choice via ``accum_dtype``).

TPU notes: X is stored int8 host-side (HBM-friendly), cast per block to
``compute_dtype`` (default bfloat16 would NOT be exact for large V per block;
default is float32 which is exact per 0/1 block up to 2^24 — and block sizes
are ≤ 2^20, so per-block products are exact; cross-block accumulation happens
in ``accum_dtype``).
"""

from __future__ import annotations

from functools import partial
from typing import Iterable

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["gramian", "gramian_accumulate", "gramian_blockwise"]


@partial(jax.jit, static_argnames=("compute_dtype", "accum_dtype"))
def gramian(x, compute_dtype=jnp.float32, accum_dtype=jnp.float32):
    """``G = X @ X.T`` for a 0/1 genotype-indicator block.

    Args:
      x: ``(n_samples, n_variants)`` array, any integer/float dtype with 0/1
        values (int8 preferred for storage).
      compute_dtype: dtype the matmul runs in on the MXU.
      accum_dtype: dtype of the returned Gramian.

    Returns:
      ``(n_samples, n_samples)`` symmetric co-occurrence matrix.
    """
    xf = x.astype(compute_dtype)
    return jnp.einsum("nv,mv->nm", xf, xf, preferred_element_type=accum_dtype)


@partial(jax.jit, static_argnames=("compute_dtype",), donate_argnums=(0,))
def gramian_accumulate(g, x_block, compute_dtype=jnp.float32):
    """One blockwise-accumulation step: ``G += X_blk @ X_blk.T``.

    This is the variant-axis streaming primitive (the reference's
    ``getSimilarityMatrixStream`` memory/shuffle tradeoff,
    ``VariantsPca.scala:248-279``, re-done TPU-style): the variant axis is
    unbounded while G stays fixed at N×N on device. ``g`` is donated so the
    accumulator updates in place in HBM.
    """
    xf = x_block.astype(compute_dtype)
    return g + jnp.einsum("nv,mv->nm", xf, xf, preferred_element_type=g.dtype)


def gramian_blockwise(
    blocks: Iterable[np.ndarray],
    n_samples: int,
    accum_dtype=jnp.float32,
    compute_dtype=jnp.float32,
    device=None,
    use_pallas=None,
):
    """Stream variant blocks through ``G += X_blk @ X_blk.T`` on device.

    Host generator → device accumulation; each block is transferred while the
    previous block's matmul runs (JAX dispatch is async, so transfer/compute
    overlap comes for free as long as blocks are pre-staged with
    ``jax.device_put``).

    Args:
      blocks: iterable of host ``(n_samples, v_blk)`` 0/1 arrays (ragged
        ``v_blk`` allowed; recompilation is avoided by padding upstream in
        :mod:`spark_examples_tpu.arrays.blocks`).
      n_samples: N — fixed by the callset index before any variant is read
        (reference ``VariantsCommon.scala:38-50``).

    Returns:
      ``(N, N)`` device Gramian.
    """
    from spark_examples_tpu.arrays.feed import device_prefetch

    default_dtypes = (
        accum_dtype == jnp.float32 and compute_dtype == jnp.float32
    )
    if use_pallas is None:
        from spark_examples_tpu.ops.pallas_gramian import pallas_enabled

        use_pallas = pallas_enabled() and jax.default_backend() == "tpu"
    # The Pallas kernel accumulates in float32 only; honor explicit dtype
    # requests by staying on the einsum path rather than silently
    # downgrading.
    if use_pallas and default_dtypes:
        return _gramian_blockwise_pallas(blocks, n_samples, device)

    g = jnp.zeros((n_samples, n_samples), dtype=accum_dtype)
    if device is not None:
        g = jax.device_put(g, device)
    for xb in device_prefetch(blocks, device=device):
        g = gramian_accumulate(g, xb, compute_dtype=compute_dtype)
    return g


def _gramian_blockwise_pallas(blocks, n_samples, device=None):
    """Pallas-kernel accumulation path (opt-in; see ops/pallas_gramian.py).

    Pads the sample axis to the kernel's tile multiple (zero rows are inert)
    and each block's variant axis likewise; trims before returning.
    """
    from spark_examples_tpu.arrays.blocks import round_up_multiple
    from spark_examples_tpu.arrays.feed import device_prefetch
    from spark_examples_tpu.ops.pallas_gramian import (
        BLOCK_N,
        BLOCK_V,
        _mirror_lower,
        _sym_accumulate_lower,
        gramian_accumulate_pallas,
        pallas_mode,
    )

    sym = pallas_mode() == "sym"
    # Sym mode accumulates the lower triangle only across all blocks and
    # mirrors ONCE at the end (per-block mirroring would spend O(N²) HBM
    # traffic per block on a bandwidth-bound kernel).
    accumulate = _sym_accumulate_lower if sym else gramian_accumulate_pallas
    n_pad = round_up_multiple(n_samples, BLOCK_N)

    def padded():
        for block in blocks:
            xb = np.asarray(block)
            v_pad = round_up_multiple(xb.shape[1], BLOCK_V)
            yield np.pad(
                xb, ((0, n_pad - n_samples), (0, v_pad - xb.shape[1]))
            )

    g = jnp.zeros((n_pad, n_pad), dtype=jnp.float32)
    if device is not None:
        g = jax.device_put(g, device)
    for xb in device_prefetch(padded(), device=device):
        g = accumulate(g, xb)
    if sym:
        g = _mirror_lower(g)
    return g[:n_samples, :n_samples]
