"""Double-centering (classical MDS / PCoA).

Reference semantics (``VariantsPca.scala:193-223``): row sums are collected
to the driver, broadcast back, and each entry is centered as

    c_ij = g_ij − rowMean_i − colMean_j + matrixMean

with ``matrixMean = ΣG / N²``. Here it is three reductions and one fused
elementwise expression under ``jit`` — no collect/broadcast round-trip; under
``pjit`` the row/column means become XLA collectives over the mesh.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["double_center"]


@jax.jit
def double_center(g):
    """Center a (possibly non-symmetric) similarity matrix G.

    Returns C with ``C[i, j] = G[i, j] - rowmean[i] - colmean[j] + grandmean``.
    For symmetric G the result is symmetric with exactly-zero row/column means
    (up to float rounding) — the property the PCoA eigendecomposition relies
    on (see :mod:`spark_examples_tpu.ops.pcoa`).
    """
    g = g.astype(jnp.promote_types(g.dtype, jnp.float32))
    rowmean = jnp.mean(g, axis=1, keepdims=True)
    colmean = jnp.mean(g, axis=0, keepdims=True)
    grandmean = jnp.mean(g)
    return g - rowmean - colmean + grandmean
