"""Pallas TPU kernel for the blockwise Gramian accumulation.

``G += X @ X.T`` is the framework's hot op. XLA's einsum already schedules
it well; this hand-written kernel exists for the cases XLA can't fuse
optimally: it reads the int8 genotype block **once per (i, j) tile pair
directly from HBM-tiled VMEM blocks**, upcasts in-register, and accumulates
into the resident G tile — no intermediate f32 copy of X in HBM (XLA's
einsum materializes the upcast when the operand is int8), which matters
because HBM bandwidth, not MXU FLOPs, bounds this op at genomics shapes
(N≈2.5k, V up to millions).

Opt-in via ``SPARK_EXAMPLES_TPU_PALLAS=1`` (or ``use_pallas=True`` in
:func:`spark_examples_tpu.ops.gramian_blockwise`) until profiled as the
default on real hardware; numerics are exact (f32 accumulation of 0/1
products) and tested against the einsum path in interpret mode.
"""

from __future__ import annotations

import os
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

__all__ = ["gramian_accumulate_pallas", "pallas_enabled", "BLOCK_N", "BLOCK_V"]

# Default tile sizes: 256×512 int8 X tiles (128 KB VMEM each) and a 256×256
# f32 G tile (256 KB) fit VMEM comfortably with double buffering.
BLOCK_N = 256
BLOCK_V = 512


def pallas_enabled() -> bool:
    return os.environ.get("SPARK_EXAMPLES_TPU_PALLAS") == "1"


def _kernel(xi_ref, xj_ref, g_in_ref, g_out_ref):
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _init():
        g_out_ref[:] = g_in_ref[:]

    xi = xi_ref[:].astype(jnp.float32)
    xj = xj_ref[:].astype(jnp.float32)
    g_out_ref[:] += jnp.dot(
        xi, xj.T, preferred_element_type=jnp.float32
    )


@partial(
    jax.jit,
    static_argnames=("block_n", "block_v", "interpret"),
    donate_argnums=(0,),
)
def gramian_accumulate_pallas(
    g,
    x_block,
    block_n: int = BLOCK_N,
    block_v: int = BLOCK_V,
    interpret: bool = False,
):
    """One accumulation step ``G += X_blk @ X_blk.T`` as a Pallas kernel.

    Args:
      g: (N, N) float32 accumulator (N padded to a multiple of block_n by
        the caller — arrays/blocks pads the sample axis already).
      x_block: (N, V) int8 block, V padded to a multiple of block_v.
    """
    n, v = x_block.shape
    assert n % block_n == 0 and v % block_v == 0, (n, v, block_n, block_v)
    gi, gv = n // block_n, v // block_v

    grid = (gi, gi, gv)
    out = pl.pallas_call(
        _kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_n, block_v), lambda i, j, k: (i, k)),
            pl.BlockSpec((block_n, block_v), lambda i, j, k: (j, k)),
            pl.BlockSpec((block_n, block_n), lambda i, j, k: (i, j)),
        ],
        out_specs=pl.BlockSpec((block_n, block_n), lambda i, j, k: (i, j)),
        out_shape=jax.ShapeDtypeStruct((n, n), jnp.float32),
        interpret=interpret,
    )(x_block, x_block, g)
    return out
