"""Pallas TPU kernel for the blockwise Gramian accumulation.

``G += X @ X.T`` is the framework's hot op. XLA's einsum already schedules
it well; this hand-written kernel exists for the cases XLA can't fuse
optimally: it reads the int8 genotype block **once per (i, j) tile pair
directly from HBM-tiled VMEM blocks**, upcasts in-register, and accumulates
into the resident G tile — no intermediate f32 copy of X in HBM (XLA's
einsum materializes the upcast when the operand is int8), which matters
because HBM bandwidth, not MXU FLOPs, bounds this op at genomics shapes
(N≈2.5k, V up to millions).

Opt-in via ``SPARK_EXAMPLES_TPU_PALLAS=dense`` (this kernel) or ``=sym``
(the triangle-only variant — ~2× fewer MXU tile matmuls, mirror deferred
to end of stream; unknown values raise) — or ``use_pallas=True`` on
:func:`spark_examples_tpu.ops.gramian_blockwise` — until profiled as the
default on real hardware (``scripts/tpu_microbench.py``); numerics are
exact (f32 accumulation of 0/1 products) and tested against the einsum
path in interpret mode.
"""

from __future__ import annotations

import os
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = [
    "gramian_accumulate_pallas",
    "gramian_accumulate_pallas_sym",
    "pallas_enabled",
    "BLOCK_N",
    "BLOCK_V",
]

# Default tile sizes: 256×512 int8 X tiles (128 KB VMEM each) and a 256×256
# f32 G tile (256 KB) fit VMEM comfortably with double buffering.
BLOCK_N = 256
BLOCK_V = 512


def pallas_enabled() -> bool:
    return pallas_mode() is not None


def pallas_mode():
    """None (off) | "dense" | "sym", from SPARK_EXAMPLES_TPU_PALLAS.

    "1"/"dense" selects :func:`gramian_accumulate_pallas`; "sym" the
    triangle-only :func:`gramian_accumulate_pallas_sym`.
    """
    val = os.environ.get("SPARK_EXAMPLES_TPU_PALLAS", "")
    if val in ("1", "dense"):
        return "dense"
    if val == "sym":
        return "sym"
    if val in ("", "0"):
        return None
    raise ValueError(
        f"SPARK_EXAMPLES_TPU_PALLAS={val!r}: expected '1'/'dense', 'sym', "
        "or unset/'0'"
    )


def _accumulate_body(k, xi_ref, xj_ref, g_in_ref, g_out_ref):
    """Shared tile body: init the output tile from the accumulator on the
    first k step, then add the (i, j) tile product."""

    @pl.when(k == 0)
    def _init():
        g_out_ref[:] = g_in_ref[:]

    xi = xi_ref[:].astype(jnp.float32)
    xj = xj_ref[:].astype(jnp.float32)
    g_out_ref[:] += jnp.dot(xi, xj.T, preferred_element_type=jnp.float32)


def _kernel(xi_ref, xj_ref, g_in_ref, g_out_ref):
    _accumulate_body(pl.program_id(2), xi_ref, xj_ref, g_in_ref, g_out_ref)


@partial(
    jax.jit,
    static_argnames=("block_n", "block_v", "interpret"),
    donate_argnums=(0,),
)
def gramian_accumulate_pallas(
    g,
    x_block,
    block_n: int = BLOCK_N,
    block_v: int = BLOCK_V,
    interpret: bool = False,
):
    """One accumulation step ``G += X_blk @ X_blk.T`` as a Pallas kernel.

    Args:
      g: (N, N) float32 accumulator (N padded to a multiple of block_n by
        the caller — arrays/blocks pads the sample axis already).
      x_block: (N, V) int8 block, V padded to a multiple of block_v.
    """
    n, v = x_block.shape
    assert n % block_n == 0 and v % block_v == 0, (n, v, block_n, block_v)
    gi, gv = n // block_n, v // block_v

    grid = (gi, gi, gv)
    out = pl.pallas_call(
        _kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_n, block_v), lambda i, j, k: (i, k)),
            pl.BlockSpec((block_n, block_v), lambda i, j, k: (j, k)),
            pl.BlockSpec((block_n, block_n), lambda i, j, k: (i, j)),
        ],
        out_specs=pl.BlockSpec((block_n, block_n), lambda i, j, k: (i, j)),
        out_shape=jax.ShapeDtypeStruct((n, n), jnp.float32),
        interpret=interpret,
    )(x_block, x_block, g)
    return out


def _sym_kernel(i_ref, j_ref, xi_ref, xj_ref, g_in_ref, g_out_ref):
    _accumulate_body(pl.program_id(1), xi_ref, xj_ref, g_in_ref, g_out_ref)


@partial(
    jax.jit,
    static_argnames=("block_n", "block_v", "interpret"),
    donate_argnums=(0,),
)
def _sym_accumulate_lower(
    g,
    x_block,
    block_n: int = BLOCK_N,
    block_v: int = BLOCK_V,
    interpret: bool = False,
):
    """One syrk-style step on the LOWER triangle only.

    The grid enumerates the T(T+1)/2 tile pairs with j ≤ i via
    scalar-prefetch index maps; only the lower triangle of the result is
    defined (upper tiles are never visited — unvisited output tiles are
    undefined, and the kernel never reads them either, so garbage cannot
    propagate). Streaming callers chain these and mirror ONCE at the end
    (:func:`_mirror_lower`) instead of paying O(N²) mirror traffic per
    block.
    """
    n, v = x_block.shape
    assert n % block_n == 0 and v % block_v == 0, (n, v, block_n, block_v)
    t, kk = n // block_n, v // block_v
    pairs = [(i, j) for i in range(t) for j in range(i + 1)]
    i_idx = jnp.asarray([p[0] for p in pairs], jnp.int32)
    j_idx = jnp.asarray([p[1] for p in pairs], jnp.int32)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(len(pairs), kk),
        in_specs=[
            pl.BlockSpec(
                (block_n, block_v), lambda p, k, i_ref, j_ref: (i_ref[p], k)
            ),
            pl.BlockSpec(
                (block_n, block_v), lambda p, k, i_ref, j_ref: (j_ref[p], k)
            ),
            pl.BlockSpec(
                (block_n, block_n),
                lambda p, k, i_ref, j_ref: (i_ref[p], j_ref[p]),
            ),
        ],
        out_specs=pl.BlockSpec(
            (block_n, block_n),
            lambda p, k, i_ref, j_ref: (i_ref[p], j_ref[p]),
        ),
    )
    return pl.pallas_call(
        _sym_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((n, n), jnp.float32),
        interpret=interpret,
    )(i_idx, j_idx, x_block, x_block, g)


@jax.jit
def _mirror_lower(g):
    """Lower-triangle-valid accumulator → full symmetric matrix."""
    return jnp.tril(g) + jnp.tril(g, -1).T


def gramian_accumulate_pallas_sym(
    g,
    x_block,
    block_n: int = BLOCK_N,
    block_v: int = BLOCK_V,
    interpret: bool = False,
):
    """Symmetric (syrk-style) accumulation: only tiles with j ≤ i compute.

    ≈2× fewer MXU tile matmuls than the dense grid of
    :func:`gramian_accumulate_pallas`; the mirror is one ``tril + trilᵀ``
    pass. Same exactness argument as the dense kernel.

    Precondition: ``g`` must be symmetric (a Gramian accumulator always
    is) — only its lower triangle is read, and the upper half of the
    result is reconstructed from the lower, so a non-symmetric ``g``'s
    upper contents would be silently replaced. Streaming callers should
    chain :func:`_sym_accumulate_lower` and mirror once instead.
    """
    return _mirror_lower(
        _sym_accumulate_lower(
            g, x_block, block_n=block_n, block_v=block_v, interpret=interpret
        )
    )
