"""Pallas scatter-accumulate kernel for the OOB-drop tile update.

The sparse engine's scatter (``ops/sparse.scatter_pairs_chunked``) is a
chunked ``lax.scan`` over XLA scatter-adds: correct and portable, but on
TPU every update serializes through the scatter unit while the
accumulating G tile bounces through HBM once per chunk. This module is
the fused alternative in the spirit of the blocked genotype-matrix
kernels of *Fast PCA of genotype matrices in Julia* (arxiv 1808.03374):
reformulate each variant's contribution as a rank-1 outer product of
one-hot *count* vectors,

    ΔG = Σ_v r_v · c_vᵀ,   r_v[t] = |{a : row_idx[v,a] = t}|,

so a chunk of C variants becomes ONE (BR, C) × (C, TC) MXU matmul with
the accumulating tile block held VMEM-resident across every carrier
chunk (the grid revisits the same output block over the chunk axis —
the tile leaves VMEM once, at the end). Out-of-bounds indices (the
carrier pad sentinel, out-of-tile carriers) match no one-hot lane and
drop exactly like the scatter's ``mode="drop"``; duplicate carriers
count multiply, exactly like scatter-add duplicate semantics. Every
update is an exact small-integer count in float32, so the result is
**bit-identical** to the scan path (pinned by tests/test_scatter_kernel).

Selection (resolved OUTSIDE any trace — the callers thread the decision
in as a static arg):

- ``SPARK_EXAMPLES_TPU_SCATTER_KERNEL=0`` — kill switch, scan always
  (the CI kernel-fallback leg runs the whole scatter suite this way);
- ``SPARK_EXAMPLES_TPU_SCATTER_KERNEL=interpret`` — force the Pallas
  kernel in interpreter mode (runs on CPU; how the tests pin
  bit-identity without a TPU);
- unset / ``1`` — auto: the compiled kernel on Mosaic-capable backends
  (TPU) when the tile geometry fits the VMEM budget
  (``SPARK_EXAMPLES_TPU_SCATTER_KERNEL_VMEM`` bytes, default 8 MiB),
  the scan path everywhere else — CPU/GPU simulations keep their exact
  historical executable.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "kernel_block_rows",
    "resolve_scatter_path",
    "scatter_pairs_kernel",
]

# float32 hardware tiling on TPU: (8, 128) min tile — kernel eligibility
# requires the G tile to divide into lane-aligned blocks.
_SUBLANE = 8
_LANE = 128

_DEFAULT_VMEM_BUDGET = 8 << 20


def _vmem_budget() -> int:
    raw = os.environ.get("SPARK_EXAMPLES_TPU_SCATTER_KERNEL_VMEM", "")
    try:
        return int(raw) if raw else _DEFAULT_VMEM_BUDGET
    except ValueError:
        return _DEFAULT_VMEM_BUDGET


def _chunk_variants() -> int:
    from spark_examples_tpu.ops.sparse import SCATTER_CHUNK_VARIANTS

    return SCATTER_CHUNK_VARIANTS


def kernel_block_rows(t_r: int, t_c: int, k: int = 0) -> Optional[int]:
    """Largest VMEM-fitting row-block size for a (t_r, t_c) f32 tile.

    The kernel holds per grid step: the g input block + output block
    (2·BR·TC·4 B), the chunk's one-hot count transients
    (C·(BR+TC)·4 B), and the two (C, K) int32 index blocks — NOT small
    at biobank carrier buckets (K=16384 alone is 33.5 MB), so ``k``
    must be charged when known (the kernel dispatch knows it at trace
    time; the resolve-time heuristic passes 0 and the dispatch
    re-checks with the real bucket, falling back to scan). Returns a
    sublane-aligned divisor of ``t_r``, or ``None`` when even the
    minimum 8-row block cannot fit — the dispatcher then uses the scan
    path rather than compile a kernel that cannot stage.
    """
    c = _chunk_variants()
    # The (C, TC) col-count transient + the two (C, K) index blocks.
    budget = _vmem_budget() - c * t_c * 4 - 2 * c * k * 4
    if budget <= 0:
        return None
    cap = budget // (2 * t_c * 4 + c * 4)  # g in+out blocks + row counts
    cap = min(t_r, (cap // _SUBLANE) * _SUBLANE)
    br = cap
    while br >= _SUBLANE:
        if t_r % br == 0:
            return br
        br -= _SUBLANE
    return None


def _kernel_eligible(tile_shape: Tuple[int, int], dtype) -> bool:
    t_r, t_c = int(tile_shape[0]), int(tile_shape[1])
    if np.dtype(dtype) != np.dtype(np.float32):
        # The one-hot count formulation is argued exact for f32 (the
        # engine's accumulator dtype); other dtypes keep the scan path.
        return False
    if t_r % _SUBLANE or t_c % _LANE:
        return False
    return kernel_block_rows(t_r, t_c) is not None


def _mosaic_backend() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # pragma: no cover — backend probe failure
        return False


def resolve_scatter_path(tile_shape: Tuple[int, int], dtype=np.float32):
    """``"scan" | "pallas" | "interpret"`` for one tile geometry.

    Resolved OUTSIDE any jit trace (same discipline as
    ``resolve_gramian_compute_dtype``): the callers cache executables
    per (geometry, path), so the env switch takes effect per
    accumulation stream, never mid-trace.
    """
    mode = (
        os.environ.get("SPARK_EXAMPLES_TPU_SCATTER_KERNEL", "")
        .strip()
        .lower()
    )
    if mode in ("0", "off", "scan"):
        return "scan"
    if not _kernel_eligible(tile_shape, dtype):
        return "scan"
    if mode == "interpret":
        return "interpret"
    if _mosaic_backend():
        return "pallas"
    return "scan"


def _scatter_kernel_body(br: int, t_c: int, k: int, c: int):
    """Kernel closure for fixed block geometry (all shapes static)."""

    def kernel(row_ref, col_ref, g_ref, out_ref):
        from jax.experimental import pallas as pl

        j = pl.program_id(1)  # carrier-chunk position (innermost)

        @pl.when(j == 0)
        def _():
            # First chunk of this row block: seed the VMEM-resident
            # accumulator from the incoming tile block; later chunks
            # revisit the same block and accumulate in place.
            out_ref[:] = g_ref[:]

        base = pl.program_id(0) * br
        ri = row_ref[:]  # (C, K) int32, OOB = sentinel >= t_r
        cj = col_ref[:]
        row_iota = (
            jax.lax.broadcasted_iota(jnp.int32, (c, br), 1) + base
        )
        col_iota = jax.lax.broadcasted_iota(jnp.int32, (c, t_c), 1)

        def body(a, carry):
            r_cnt, c_cnt = carry
            r = jax.lax.dynamic_slice(ri, (0, a), (c, 1))
            cc = jax.lax.dynamic_slice(cj, (0, a), (c, 1))
            r_cnt = r_cnt + (row_iota == r).astype(jnp.float32)
            c_cnt = c_cnt + (col_iota == cc).astype(jnp.float32)
            return r_cnt, c_cnt

        r_cnt, c_cnt = jax.lax.fori_loop(
            0,
            k,
            body,
            (
                jnp.zeros((c, br), jnp.float32),
                jnp.zeros((c, t_c), jnp.float32),
            ),
        )
        # Σ_v r_v · c_vᵀ over the chunk: one MXU contraction — counts
        # are exact small integers in f32, so the add is exact.
        # precision=HIGHEST: the default matmul precision routes f32
        # operands through bf16 multiplies on TPU, which would round
        # duplicate-carrier counts above 256 and break the
        # bit-identity contract exactly on the backend that
        # auto-selects this kernel.
        out_ref[:] += jax.lax.dot_general(
            r_cnt,
            c_cnt,
            (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )

    return kernel


def scatter_pairs_kernel(g, row_idx, col_idx, interpret: bool = False):
    """``g[row_idx[v,a], col_idx[v,b]] += 1`` — the Pallas formulation.

    Drop-in for :func:`spark_examples_tpu.ops.sparse.scatter_pairs_chunked`
    (same operands, same OOB-drop and duplicate semantics, bit-identical
    result); callers must have resolved eligibility via
    :func:`resolve_scatter_path` first. Traceable under jit/shard_map.
    The resolve-time budget check cannot see the carrier bucket K (it
    varies per window); this dispatch re-checks with the REAL K and
    falls back to the scan body — bit-identical — when the index
    blocks push the grid step over the VMEM budget.
    """
    from jax.experimental import pallas as pl

    t_r, t_c = g.shape
    v_pad, k = row_idx.shape
    c = _chunk_variants()
    br = kernel_block_rows(t_r, t_c, k)
    if br is None:
        from spark_examples_tpu.ops.sparse import scatter_pairs_chunked

        return scatter_pairs_chunked(g, row_idx, col_idx)
    grid = (t_r // br, v_pad // c)
    return pl.pallas_call(
        _scatter_kernel_body(br, t_c, k, c),
        grid=grid,
        in_specs=[
            pl.BlockSpec((c, k), lambda i, j: (j, 0)),
            pl.BlockSpec((c, k), lambda i, j: (j, 0)),
            pl.BlockSpec((br, t_c), lambda i, j: (i, 0)),
        ],
        out_specs=pl.BlockSpec((br, t_c), lambda i, j: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((t_r, t_c), g.dtype),
        interpret=interpret,
    )(row_idx, col_idx, g)
