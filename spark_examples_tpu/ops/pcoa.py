"""Principal-coordinate analysis: eigendecomposition of the centered Gramian.

Reference pipeline (``VariantsPca.scala:224-231``): the double-centered rows
are wrapped in an MLlib ``RowMatrix`` and ``computePrincipalComponents(k)``
runs — which (a) forms the *covariance matrix of the rows* and (b)
eigendecomposes it on the driver via Breeze/LAPACK, returning the top-k
eigenvectors as an N×k matrix whose row i is emitted as sample i's
coordinates (``VariantsPca.scala:227-230``).

Equivalence used here: the double-centered matrix C is symmetric with
exactly-zero column means, so the covariance of its rows is
``cov = CᵀC/(n−1) = C²/(n−1)``. C² shares eigenvectors with C and squares
the eigenvalues, so MLlib's principal components are exactly the
eigenvectors of C ordered by **|λ| descending** — one ``eigh`` of C instead
of forming C². ``mllib_principal_components_reference`` implements MLlib's
literal composition in numpy f64 and is the golden the fast path is tested
against (the BASELINE 1e-4 parity bar, modulo eigenvector sign which is
arbitrary in any LAPACK-family solver and normalized deterministically here).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from spark_examples_tpu.ops.centering import double_center

__all__ = [
    "DEFAULT_RANDOMIZED_OVERSAMPLE",
    "DEFAULT_SKETCH_POWER_ITERS",
    "SpectralGapWarning",
    "check_spectral_gap",
    "randomized_panel_width",
    "topk_with_gap_check",
    "pcoa",
    "principal_components",
    "mllib_principal_components_reference",
    "normalize_eigvec_signs",
]

# The ONE oversampling default every randomized top-k consumer derives
# from: the exact sharded finish (parallel.sharded.topk_eig_randomized)
# and the Gramian-free sketch engine (ops/sketch.py, --sketch-oversample)
# both resolve their panel width through randomized_panel_width with
# this value — a drifted copy in either caller would silently change
# which Ritz pairs exist for the gap check.
DEFAULT_RANDOMIZED_OVERSAMPLE = 8

# Extra full streamed passes the sketch engine runs with Ω ← orth(Y)
# between them (--sketch-power-iters). 0 = ONE pass over the windows —
# the cold-stream overlap discipline (arxiv 1302.4332); the tolerance
# goldens use ≥ 2 where the approximation regime needs them.
DEFAULT_SKETCH_POWER_ITERS = 0


def randomized_panel_width(
    n: int, k: int, oversample: int = DEFAULT_RANDOMIZED_OVERSAMPLE
) -> int:
    """Panel width p for a randomized top-k eigensolve — the ONE place
    the k+1-values calling convention lives.

    Every consumer of randomized subspace iteration
    (:func:`spark_examples_tpu.parallel.sharded.topk_eig_randomized`,
    and through it the sharded finish — and the Gramian-free sketch
    engine of :mod:`spark_examples_tpu.ops.sketch`, whose Ω panel and
    Nyström core are sized by exactly this width) needs the
    oversampled panel to
    carry AT LEAST ``min(k+1, n)`` Ritz pairs: ``k`` for the returned
    components plus one past the gap for :func:`check_spectral_gap`
    (which silently returns when no value past index k−1 exists — the
    silent-skip this helper exists to make impossible). Before this
    helper the ``k + oversample`` arithmetic was duplicated implicitly
    at call sites, so an ``oversample=0`` caller would both shrink the
    subspace below the convention AND disable the degeneracy warning
    without a trace. Centralized: ``min(n, k + max(oversample, 1))`` —
    the floor guarantees the k+1-th value whenever the spectrum has one
    (n > k), and the returned width is what callers must allocate and
    slice against (``vecs[:, :k]``/``vals[:k]`` can then never drop a
    requested component).
    """
    if k < 1:
        raise ValueError(f"top-k eigensolve needs k >= 1, got {k}")
    return min(n, k + max(int(oversample), 1))


class SpectralGapWarning(UserWarning):
    """Top-k eigenvalue gap is near-degenerate; coordinates are unstable."""


def check_spectral_gap(vals, k: int, warn_ratio: float = 0.95, timer=None):
    """Warn loudly when the k-th eigen-gap is near-degenerate.

    ``vals`` are |λ|-ordered eigen/Ritz values with at least one entry past
    index k−1 (callers request k+1 values; the randomized path's
    oversampled panel has them anyway). A ratio |λ_{k+1}|/|λ_k| near 1
    means the top-k eigenbasis is rotation-ambiguous — for dense ``eigh``
    exactly as for randomized iteration: a weakly structured cohort has no
    well-defined PC2, and that must be loud, not silent (round-2 verdict
    weak #5). The ratio also lands in the stage-timer report when a
    ``timer`` (utils.tracing.StageTimer) is passed.
    """
    import warnings

    if len(vals) <= k:
        return  # caller could not supply a value past the gap
    lam_k, lam_next = abs(float(vals[k - 1])), abs(float(vals[k]))
    if lam_k == 0.0:
        return  # rank-deficient below k: coordinates there are zeros
    ratio = lam_next / lam_k
    if timer is not None:
        timer.note(f"spectral gap |λ{k + 1}|/|λ{k}| = {ratio:.4f}")
    if ratio > warn_ratio:
        warnings.warn(
            f"near-degenerate spectral gap: |λ{k + 1}|/|λ{k}| = {ratio:.4f}"
            f" > {warn_ratio}. The top-{k} eigenbasis is rotation-ambiguous"
            " (for dense eigh too) — principal coordinates beyond the"
            " well-separated eigenvalues are unstable on this cohort.",
            SpectralGapWarning,
            stacklevel=3,
        )


def topk_with_gap_check(eig_fn, k, n, timer=None, vals_are_squared=False):
    """Request k+1 eigenpairs, gap-check past k, slice back to k.

    The one place holding the pattern every dense eig call site needs:
    the ``min(k+1, n)`` clamp, passing the UNsliced values to
    :func:`check_spectral_gap`, then trimming coords/vals to k.
    ``eig_fn(kk)`` returns ``(coords (n, kk), vals (kk,))`` ordered by
    magnitude descending. ``vals_are_squared``: MLlib-literal covariance
    eigenvalues are λ(C)²/(n−1), so their ratio is the square of the
    centered-Gramian gap ratio every other tier checks — take the sqrt
    first so the 0.95 threshold means the same cohort everywhere.
    """
    coords, vals = eig_fn(min(k + 1, n))
    v = np.abs(np.asarray(vals, dtype=np.float64))
    if vals_are_squared:
        v = np.sqrt(v)
    check_spectral_gap(v, k, timer=timer)
    return coords[:, :k], vals[:k]


def normalize_eigvec_signs(vecs):
    """Deterministic sign convention: largest-|entry| of each column > 0.

    Eigenvector signs are arbitrary; LAPACK/Breeze/XLA may disagree. Fixing
    the sign so the largest-magnitude component of each column is positive
    (ties broken by lowest row index via argmax) makes output stable across
    backends and is the convention the parity tests compare under.
    """
    if isinstance(vecs, np.ndarray):
        idx = np.argmax(np.abs(vecs), axis=0)
        signs = np.sign(vecs[idx, np.arange(vecs.shape[1])])
        signs = np.where(signs == 0, 1.0, signs)
        return vecs * signs
    idx = jnp.argmax(jnp.abs(vecs), axis=0)
    signs = jnp.sign(vecs[idx, jnp.arange(vecs.shape[1])])
    signs = jnp.where(signs == 0, 1.0, signs)
    return vecs * signs


@partial(jax.jit, static_argnames=("k",))
def principal_components(c, k):
    """Top-k principal components of a double-centered symmetric matrix.

    Returns ``(coords, eigvals)``: ``coords`` is N×k (row i = sample i's
    coordinates, matching the reference's use of the MLlib PC matrix rows),
    ``eigvals`` the corresponding eigenvalues of C (note: MLlib's reported
    eigenvalues would be these squared over n−1; the *vectors* are what the
    reference emits). Ordered by |λ| descending, signs normalized.
    """
    w, v = jnp.linalg.eigh(c)
    order = jnp.argsort(-jnp.abs(w))[:k]
    vecs = normalize_eigvec_signs(v[:, order])
    return vecs, w[order]


@partial(jax.jit, static_argnames=("k", "scale"))
def _pcoa_jit(g, k, scale):
    c = double_center(g)
    coords, w = principal_components(c, k)
    if scale:
        coords = coords * jnp.sqrt(jnp.maximum(w, 0.0))
    return coords, w


def pcoa(g, k, scale=False):
    """Full PCoA of a raw similarity Gramian: center → eigendecompose.

    Args:
      g: (N, N) similarity/co-occurrence matrix.
      k: number of principal coordinates.
      scale: if True, scale coordinates by sqrt(max(λ, 0)) — classical
        PCoA/Torgerson coordinates. The reference does NOT scale (it emits
        raw eigenvector entries), so the default is False.

    Returns:
      ``(coords, eigvals)`` as in :func:`principal_components`.

    The jitted body lives in ``_pcoa_jit``; this wrapper exists so the
    telemetry session (when active) can record the kernel's compile time
    and XLA cost analysis per call signature.
    """
    from spark_examples_tpu import obs
    from spark_examples_tpu.obs.xla import record_compiled

    record_compiled("pcoa", _pcoa_jit, g, k, scale)
    with obs.span("pcoa", n=int(g.shape[0]), k=int(k)):
        return _pcoa_jit(g, k, scale)


def mllib_principal_components_reference(g, k):
    """Literal numpy-f64 emulation of the reference math — the golden path.

    Mirrors ``VariantsPca.scala:198-231`` + MLlib ``RowMatrix
    .computePrincipalComponents``: double-center G, form the row covariance
    ``(CᵀC − n·μμᵀ)/(n−1)`` exactly as MLlib's ``computeCovariance`` does,
    eigendecompose, take top-k by eigenvalue descending, normalize signs.
    Runs on the host in float64 — the analog of the reference's driver-side
    Breeze/LAPACK eig.
    """
    g = np.asarray(g, dtype=np.float64)
    n = g.shape[0]
    rowmean = g.mean(axis=1, keepdims=True)
    colmean = g.mean(axis=0, keepdims=True)
    c = g - rowmean - colmean + g.mean()
    mu = c.mean(axis=0, keepdims=True)
    cov = (c.T @ c - n * (mu.T @ mu)) / (n - 1)
    w, v = np.linalg.eigh(cov)
    order = np.argsort(-w)[:k]
    return normalize_eigvec_signs(v[:, order]), w[order]
