"""Gramian-free randomized sketch PCA: ``--pca-mode sketch``.

Every other PCA engine — fused, streamed dense, host-local sparse,
pod-sparse — materializes N×N tiles of G = XXᵀ, which at N = 10⁶ is
4 TB of f32: the footprint bound (:meth:`VariantsPcaDriver.
_sparse_host_g_bytes`) refuses long before the biobank north star.
The randomized-subspace literature (arxiv 1808.03374's genotype PCA,
Halko-Martinsson-Tropp) recovers the top-k eigenpairs of the centered
Gramian C = H·G·H (H the centering projector) from streamed products
alone. This module is that engine for the 0/1 indicator Gramian:

    C·Ω = Σ_w  H · X_w · (X_wᵀ · (H·Ω))

so each CSR carrier window contributes ``Y += X_w · (X_wᵀ · Ω̃)`` with
``Ω̃ = Ω − colmean(Ω)`` — two window-sized products, never an N×N tile
— and the left centering is one column-mean subtraction of the FINAL
panel (padding rows masked back to zero). The accumulation is a sum
over windows, so it is invariant to window arrival order: the
completion-order ingest pipeline and the pod protocol's per-step
gangs need no re-sorting (pinned by the shuffled-order goldens).

Window routing reuses the sparse engine's machinery wholesale
(:mod:`spark_examples_tpu.ops.sparse`): the density-route switch
(:func:`window_route`), the padded carrier matrix with OOB sentinel +
``mode="drop"`` scatter for sparse windows, and the pow2
``dense_panel_width`` densify + MXU matmul pair for dense windows.
Sparse-route cost is O(nnz·l) per window (l = k+p panel columns, from
:func:`spark_examples_tpu.ops.pcoa.randomized_panel_width` — the ONE
panel-width policy); memory is O(N·l) everywhere, never O(N²)
(:func:`sketch_host_bytes` is the documented bound, asserted by test).

The finish is the shifted Nyström eigensolve (Tropp et al.): with
Y = C·Ω and shift ν ≈ √n·eps_f32·‖Y‖_F,

    Y_ν = Y + ν·Ω;  Q·R = qr(Y_ν);  B = sym(Ωᵀ·Y_ν);  L = chol(B)
    U₁·Σ·Vᵀ = svd(R·L⁻ᵀ);  λ̂ = max(Σ² − ν, 0);  V̂ = Q·U₁

Meshless runs do the whole finish host-side in f64; mesh runs replace
the tall QR with the shard_map TSQR over the pod
(:func:`spark_examples_tpu.parallel.sharded.sketch_tsqr`) and keep
only the (k+p)×(k+p) core on the host. ``--sketch-power-iters q``
re-streams the windows q extra times with Ω ← orth(Y) between passes
(the classic accuracy knob); the default 0 keeps the one-streamed-pass
discipline of the cold-stream pipeline (arxiv 1302.4332).

Spectrum-tolerance contract (the PairHMM-style pinned bars, asserted
by tests/test_sketch.py against the exact path at small N):

- FULL-RANK REGIME — panel covers the whole space (l ≥ n, e.g.
  ``--sketch-oversample`` ≥ n−k): the Nyström reconstruction is exact
  up to floating-point roundoff. Top-k eigenvalues match the exact
  path within ``SKETCH_FULLRANK_RTOL`` relative; sign-normalized
  coordinates within ``SKETCH_FULLRANK_ATOL`` absolute per entry.
- TOP-K REGIME — l < n with ≥ 2 power iterations on a cohort whose
  spectrum has a clear gap past k: top-k eigenvalues within
  ``SKETCH_TOPK_RTOL`` relative; coordinates within
  ``SKETCH_TOPK_ATOL`` absolute per entry.

Runs are REPRODUCIBLE, not bit-identical to exact: Ω is seeded
(``--sketch-seed``, threaded from the CLI), so the same seed + same
topology reproduces the same coordinates bit-for-bit, while different
seeds agree only within the tolerance contract.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Callable, Iterable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from spark_examples_tpu.ops.sparse import (
    DEFAULT_SPARSE_DENSITY_THRESHOLD,
    SCATTER_CHUNK_VARIANTS,
    _pad_rows_for_scan,
    dense_panel_width,
    padded_carrier_matrix,
    window_route,
)

__all__ = [
    "SKETCH_FULLRANK_ATOL",
    "SKETCH_FULLRANK_RTOL",
    "SKETCH_TOPK_ATOL",
    "SKETCH_TOPK_RTOL",
    "SketchPanel",
    "gaussian_test_matrix",
    "sketch_eig",
    "sketch_host_bytes",
    "sketch_panel_blockwise",
]

# Tolerance contract (module docstring has the regime definitions).
# Full-rank: the only error sources are f32 accumulation roundoff and
# the ν shift — both orders below these bars at test N (≤ 256).
SKETCH_FULLRANK_RTOL = 1e-3
SKETCH_FULLRANK_ATOL = 1e-3
# Top-k: randomized approximation error dominates; the bars hold for
# gapped spectra with ≥ 2 power iterations (the test fixtures).
SKETCH_TOPK_RTOL = 5e-2
SKETCH_TOPK_ATOL = 5e-2


def sketch_host_bytes(n: int, l: int) -> int:
    """The sketch engine's documented host-footprint bound: O(N·l)
    f32/f64 panels — Y (with its row-sums companion column), Ω, and the
    centered Ω̃ working copy — never O(N²). The bench scale-out leg
    emits this next to ``ru_maxrss`` provenance, and the footprint test
    asserts no single allocation on the sketch path exceeds it."""
    # y (l+1 cols, f32) + omega (f32) + centered copy (f32) + the f64
    # finish copies of y and omega.
    return 4 * n * (3 * (l + 1)) + 8 * n * (2 * l)


def gaussian_test_matrix(n: int, width: int, seed: int) -> np.ndarray:
    """Seeded (n, width) f32 Gaussian Ω — the CLI-threaded
    ``--sketch-seed`` makes every run reproducible, and every process
    of a pod derives the IDENTICAL matrix (the accumulation is a
    collective over replicated panels, so Ω divergence would be silent
    corruption)."""
    rng = np.random.default_rng(seed)
    return rng.standard_normal((n, width)).astype(np.float32)


@dataclasses.dataclass
class SketchPanel:
    """The sketch ingest product — what ``--pca-mode sketch`` returns
    from ``ingest_gramian`` in place of an (N, N) Gramian.

    ``y`` is the centered sketch C·Ω_final and ``omega`` the FINAL test
    matrix (orth(Y) after power iterations, Ω̃ otherwise) — host f64
    arrays always; mesh runs (``mesh`` set, routing the finish through
    the pod TSQR) carry n_padded rows with zeroed padding. ``row_sums``
    carries G's row sums — accumulated by a ones companion column on
    the first pass — so the non-zero-rows parity print survives
    without G."""

    y: Any
    omega: Any
    row_sums: np.ndarray
    n: int
    k: int
    l: int
    seed: int
    power_iters: int
    mesh: Any = None
    host_peak_bytes: int = 0


def _note_sketch_window(route: str, count: int = 1) -> None:
    """Per-window sketch telemetry (one registration site per metric,
    GL003; the label set is enforced by
    ``validate_trace._LABELED_COUNTERS``). ``count`` follows the pod
    protocol's coalesced gangs exactly as the sparse engine's counter
    does."""
    from spark_examples_tpu import obs

    obs.get_registry().counter(
        "sketch_windows_total",
        "CSR windows applied to the randomized sketch panel",
    ).labels(route=route).inc(count)


@partial(jax.jit, donate_argnums=(0,))
def _sketch_scatter_update(y: Any, omega: Any, idx: Any) -> Any:
    """One sparse-route window into the panel: ``Y += X·(Xᵀ·Ω̃)``
    without forming X. ``idx`` is the padded carrier matrix
    ``(V_pad, k_bucket)`` (V_pad a multiple of the scan chunk,
    sentinel = y rows, so padded entries gather zero rows and their
    scatter drops). Per variant v the update adds
    ``t_v = Σ_{a} Ω̃[idx[v, a]]`` back to every carrier row — the
    scan bounds the transient at ``chunk · k_bucket · l``."""
    shape = (
        idx.shape[0] // SCATTER_CHUNK_VARIANTS,
        SCATTER_CHUNK_VARIANTS,
        idx.shape[1],
    )

    def body(acc: Any, ci: Any) -> Tuple[Any, None]:
        rows = omega.at[ci].get(mode="fill", fill_value=0)
        t = jnp.sum(rows, axis=1)
        upd = jnp.broadcast_to(t[:, None, :], rows.shape)
        return acc.at[ci].add(upd, mode="drop"), None

    y, _ = jax.lax.scan(body, y, idx.reshape(shape))
    return y


@partial(jax.jit, donate_argnums=(0,))
def _sketch_dense_update(y: Any, omega: Any, xp: Any) -> Any:
    """One dense-route window: unpack the bit-packed indicator panel
    (the same pow2-bucketed packed bytes the Gramian MXU path ships)
    and ride two MXU matmuls — ``Y += X·(Xᵀ·Ω̃)``."""
    from spark_examples_tpu.ops.gramian import unpack_indicator_block

    xb = unpack_indicator_block(xp, 8 * xp.shape[1]).astype(y.dtype)
    return y + xb @ (xb.T @ omega)


def _center_columns(
    panel: np.ndarray, n: int
) -> np.ndarray:
    """Subtract per-column means over the n REAL rows; rows past n
    (mesh padding) are zeroed back (C's padded block is zero, so the
    centered sketch must vanish there too)."""
    out = panel - panel[:n].mean(axis=0, keepdims=True)
    out[n:] = 0.0
    return out


def _augmented_omega(
    omega: np.ndarray, n: int, first_pass: bool
) -> np.ndarray:
    """The streamed right-hand panel: centered Ω̃ plus one companion
    column — all-ones on the first pass (its accumulation is
    ``X·(Xᵀ·1)`` = G's row sums, the parity-print vector), zeros on
    power-iteration re-passes (inert, but keeps the per-window
    executable geometry identical across passes — no retrace)."""
    aug = np.zeros((omega.shape[0], omega.shape[1] + 1), omega.dtype)
    aug[:, :-1] = _center_columns(omega, n)
    if first_pass:
        aug[:n, -1] = 1.0
    return aug


def sketch_panel_blockwise(
    windows_factory: Callable[[], Iterable[Tuple[np.ndarray, np.ndarray]]],
    n_samples: int,
    k: int,
    oversample: Optional[int] = None,
    power_iters: Optional[int] = None,
    seed: int = 0,
    density_threshold: float = DEFAULT_SPARSE_DENSITY_THRESHOLD,
    block_variants: Optional[int] = None,
) -> SketchPanel:
    """Stream CSR carrier windows into a single-device (N, k+p) sketch
    panel — the meshless sketch engine (mesh runs go through
    :func:`spark_examples_tpu.parallel.sharded.sharded_sketch_panel`).

    ``windows_factory`` returns a FRESH window iterator per call —
    power iterations re-stream the cohort once per extra pass. Routing,
    padding, and bucketing reuse the sparse engine's helpers verbatim,
    so the per-window executable census stays O(log) by the same
    bucket arguments (GL012).
    """
    from spark_examples_tpu import obs
    from spark_examples_tpu.arrays.blocks import (
        DEFAULT_BLOCK_VARIANTS,
        _check_indices,
        _densify_window,
    )
    from spark_examples_tpu.ops.gramian import pack_indicator_block
    from spark_examples_tpu.ops.pcoa import (
        DEFAULT_SKETCH_POWER_ITERS,
        randomized_panel_width,
    )

    if oversample is None:
        oversample = _default_sketch_oversample()
    if power_iters is None:
        power_iters = DEFAULT_SKETCH_POWER_ITERS
    width = block_variants or DEFAULT_BLOCK_VARIANTS
    l = randomized_panel_width(n_samples, k, oversample)
    omega0 = gaussian_test_matrix(n_samples, l, seed)
    omega_cur = omega0
    row_sums = np.zeros(n_samples, dtype=np.float64)
    y_host: Optional[np.ndarray] = None
    for p in range(power_iters + 1):
        first = p == 0
        aug = _augmented_omega(omega_cur, n_samples, first_pass=first)
        om_dev = jnp.asarray(aug)
        y = jnp.zeros((n_samples, l + 1), dtype=jnp.float32)
        with obs.span(
            "gramian.sketch.accumulate",
            n=n_samples,
            l=l,
            sketch_pass=p,
        ):
            for window_idx, lens in windows_factory():
                lens = np.asarray(lens)
                _check_indices(np.asarray(window_idx), n_samples)
                route = window_route(
                    lens, n_samples, density_threshold
                )
                nnz = int(lens.sum())
                with obs.span(
                    "gramian.sketch.window",
                    route=route,
                    nnz=nnz,
                    variants=int(lens.size),
                ):
                    if route == "scatter":
                        idx = padded_carrier_matrix(
                            window_idx,
                            lens,
                            sentinel=n_samples,
                            n_rows=_pad_rows_for_scan(lens.size),
                        )
                        y = _sketch_scatter_update(
                            y, om_dev, jnp.asarray(idx)
                        )
                    else:
                        xp = pack_indicator_block(
                            _densify_window(
                                window_idx,
                                lens,
                                n_samples,
                                dense_panel_width(
                                    int(lens.size), width
                                ),
                            )
                        )
                        y = _sketch_dense_update(
                            y, om_dev, jnp.asarray(xp)
                        )
                _note_sketch_window(route)
        y_np = np.asarray(y, dtype=np.float64)
        y_np = _merge_partial_panels(y_np)
        if first:
            row_sums = y_np[:, -1].copy()
        y_host = _center_columns(y_np[:, :-1], n_samples)
        if p < power_iters:
            # Ω ← orth(Y): the next pass streams against an
            # orthonormal (re-centered) basis of the current range.
            q, _ = np.linalg.qr(y_host)
            omega_cur = q.astype(np.float32)
    omega_final = (
        _center_columns(
            omega_cur.astype(np.float64), n_samples
        )
        if power_iters
        else _center_columns(
            omega0.astype(np.float64), n_samples
        )
    )
    return SketchPanel(
        y=y_host,
        omega=omega_final,
        row_sums=row_sums,
        n=n_samples,
        k=k,
        l=l,
        seed=seed,
        power_iters=power_iters,
        host_peak_bytes=sketch_host_bytes(n_samples, l),
    )


def _default_sketch_oversample() -> int:
    from spark_examples_tpu.ops.pcoa import DEFAULT_RANDOMIZED_OVERSAMPLE

    return DEFAULT_RANDOMIZED_OVERSAMPLE


def _merge_partial_panels(y_np: np.ndarray) -> np.ndarray:
    """Multi-controller runs whose panel is NOT collectively
    accumulated (meshless, or a host-local mesh fed per-host manifest
    slices) hold per-host partial sums — merge over DCN. The
    process-spanning pod accumulator never calls this (its every step
    was already a collective over the full window set)."""
    if jax.process_count() == 1:
        return y_np
    from spark_examples_tpu.parallel.distributed import (
        allreduce_gramian,
    )

    return np.asarray(allreduce_gramian(y_np))


def _nystrom_core(
    r: np.ndarray, b: np.ndarray, nu: float
) -> Tuple[np.ndarray, np.ndarray]:
    """The (k+p)×(k+p) host-f64 core shared by the meshless and TSQR
    finishes: B's Cholesky whitening, the small SVD, and the shift
    removal. Returns ``(u1, vals)`` with ``vals`` descending."""
    b = (b + b.T) / 2.0
    jitter = 0.0
    eye = np.eye(b.shape[0])
    for attempt in range(4):
        try:
            chol = np.linalg.cholesky(b + jitter * eye)
            break
        except np.linalg.LinAlgError:
            base = max(np.trace(b) / b.shape[0], nu, 1e-30)
            jitter = base * (1e-12 * 10 ** (2 * attempt))
    else:
        raise np.linalg.LinAlgError(
            "sketch core matrix B = sym(Omega^T Y_nu) is not positive "
            "definite after jitter retries — the sketch panel is "
            "numerically degenerate (all-zero cohort windows?)"
        )
    # m = R·L⁻ᵀ via one triangular solve: L·Z = Rᵀ ⇒ m = Zᵀ.
    m = np.linalg.solve(chol, r.T).T
    u1, s, _ = np.linalg.svd(m)
    vals = np.maximum(s * s - nu, 0.0)
    return u1, vals


def sketch_eig(
    panel: SketchPanel, k: int, timer: Any = None
) -> Tuple[np.ndarray, np.ndarray]:
    """Top-k eigenpairs of the centered Gramian from a sketch panel.

    Returns ``(coords, vals)``: coords (n, k) sign-normalized unit
    eigenvector entries — the same surface the exact finishes emit —
    and the k approximate eigenvalues. The spectral-gap check runs on
    the full l-wide Ritz spectrum (l ≥ k+1 by the panel-width floor),
    exactly like every exact tier."""
    from spark_examples_tpu import obs
    from spark_examples_tpu.ops.pcoa import (
        check_spectral_gap,
        normalize_eigvec_signs,
    )

    with obs.span("gramian.sketch.finish", n=panel.n, k=k, l=panel.l):
        if panel.mesh is not None:
            from spark_examples_tpu.parallel.sharded import (
                sharded_sketch_finish,
            )

            coords, vals = sharded_sketch_finish(panel, k)
        else:
            y, omega = panel.y, panel.omega
            norm = float(np.linalg.norm(y))
            if norm == 0.0:
                # All-zero cohort: C = 0, every coordinate is 0.
                return (
                    np.zeros((panel.n, k)),
                    np.zeros(k),
                )
            nu = np.sqrt(panel.n) * np.finfo(np.float32).eps * norm
            y_nu = y + nu * omega
            q, r = np.linalg.qr(y_nu)
            b = omega.T @ y_nu
            u1, vals = _nystrom_core(r, b, nu)
            coords = q @ u1
        check_spectral_gap(vals, k, timer=timer)
        coords = normalize_eigvec_signs(
            np.asarray(coords)[: panel.n, :k]
        )
        return coords, np.asarray(vals)[:k]
