"""Device math under ``jit``: the dense kernels of the framework.

The reference computes its N×N sample co-occurrence ("similarity") matrix by
an O(k²) scalar double loop per variant into a per-task Breeze DenseMatrix
(reference ``VariantsPca.scala:184-189``) followed by a Spark ``reduceByKey``
shuffle of all N² entries, and eigendecomposes on the driver JVM via
Breeze/LAPACK (``VariantsPca.scala:225-226``). Here the same math is a batched
matmul on the MXU: ``G = X @ X.T`` over dense 0/1 genotype-indicator blocks,
blockwise-accumulated over the variant axis, then double-centering and
``eigh`` — all fused under ``jit``.
"""

from spark_examples_tpu.ops.gramian import (
    gramian,
    gramian_accumulate,
    gramian_blockwise,
)
from spark_examples_tpu.ops.centering import double_center
from spark_examples_tpu.ops.pcoa import (
    pcoa,
    principal_components,
    mllib_principal_components_reference,
    normalize_eigvec_signs,
    randomized_panel_width,
)
from spark_examples_tpu.ops.sparse import (
    sparse_gramian_accumulate,
    sparse_gramian_blockwise,
)

__all__ = [
    "gramian",
    "gramian_accumulate",
    "gramian_blockwise",
    "double_center",
    "pcoa",
    "principal_components",
    "mllib_principal_components_reference",
    "normalize_eigvec_signs",
    "randomized_panel_width",
    "sparse_gramian_accumulate",
    "sparse_gramian_blockwise",
]
