"""ctypes loader for the native ingest core, with transparent fallback.

``load()`` returns the compiled library handle or ``None``; callers keep a
pure-Python path so the framework runs on hosts without a toolchain (set
``SPARK_EXAMPLES_TPU_NO_NATIVE=1`` to force the fallback — used by tests to
cover both paths).
"""

from __future__ import annotations

import contextlib
import ctypes
import os
import subprocess
import threading
from typing import Optional

__all__ = ["load", "native_available", "force_fallback", "CohortCsr"]


@contextlib.contextmanager
def force_fallback():
    """Force the pure-Python/numpy fallback paths for the duration.

    Sets the ``SPARK_EXAMPLES_TPU_NO_NATIVE`` kill switch — which
    ``load()`` re-checks on every call, so the toggle works mid-process
    — and RESTORES any pre-existing value on exit (the CI fallback lane
    exports it run-wide; popping it would silently re-enable the native
    path for everything after the first caller). The one helper the
    tests and bench share, so the env contract can't drift between
    copies."""
    old = os.environ.get("SPARK_EXAMPLES_TPU_NO_NATIVE")
    os.environ["SPARK_EXAMPLES_TPU_NO_NATIVE"] = "1"
    try:
        yield
    finally:
        if old is None:
            os.environ.pop("SPARK_EXAMPLES_TPU_NO_NATIVE", None)
        else:
            os.environ["SPARK_EXAMPLES_TPU_NO_NATIVE"] = old


class CohortCsr(ctypes.Structure):
    """Mirror of the C CohortCsr result struct (genomics_native.cpp)."""

    _fields_ = [
        ("n_variants", ctypes.c_int64),
        ("n_calls", ctypes.c_int64),
        ("n_contigs", ctypes.c_int64),
        ("n_vsids", ctypes.c_int64),
        ("error", ctypes.c_int64),
        ("error_line", ctypes.c_int64),
        ("starts", ctypes.POINTER(ctypes.c_int64)),
        ("ends", ctypes.POINTER(ctypes.c_int64)),
        ("contig_code", ctypes.POINTER(ctypes.c_int32)),
        ("vsid_code", ctypes.POINTER(ctypes.c_int32)),
        ("afs", ctypes.POINTER(ctypes.c_double)),
        ("offsets", ctypes.POINTER(ctypes.c_int64)),
        ("ords", ctypes.POINTER(ctypes.c_int32)),
        ("contig_blob", ctypes.POINTER(ctypes.c_char)),
        ("contig_offs", ctypes.POINTER(ctypes.c_int64)),
        ("vsid_blob", ctypes.POINTER(ctypes.c_char)),
        ("vsid_offs", ctypes.POINTER(ctypes.c_int64)),
        ("ref_blob", ctypes.POINTER(ctypes.c_char)),
        ("ref_offs", ctypes.POINTER(ctypes.c_int64)),
        ("alt_blob", ctypes.POINTER(ctypes.c_char)),
        ("alt_offs", ctypes.POINTER(ctypes.c_int64)),
    ]

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "genomics_native.cpp")
_SO = os.path.join(_HERE, "_genomics_native.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> bool:
    # Compile to a process-unique temp path and rename atomically:
    # concurrent builders (multi-host launch, pytest-xdist) must never
    # leave a half-written .so where another process dlopens it.
    tmp = f"{_SO}.{os.getpid()}.tmp"
    cmd = [
        "g++",
        "-O3",
        "-shared",
        "-fPIC",
        "-std=c++17",
        "-pthread",
        _SRC,
        "-o",
        tmp,
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, _SO)
        return True
    except (OSError, subprocess.SubprocessError):
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if os.environ.get("SPARK_EXAMPLES_TPU_NO_NATIVE") == "1":
        return None
    if _tried:  # lock-free fast path once resolved (hot-loop callers)
        return _lib
    with _lock:
        if _tried:
            return _lib
        # Alternate-library override (scripts/sanitize_native.sh): point
        # the loader at a sanitizer-instrumented build WITHOUT touching
        # the canonical .so — overwriting it in place would leave an
        # ASan-instrumented library (which needs its runtime preloaded)
        # for the next uninstrumented run to dlopen and die on. Checked
        # BEFORE _tried is set: an override that cannot load must raise
        # on EVERY call — caching the failure would hand every later
        # caller a silent numpy fallback, the exact green-while-
        # covering-nothing mode the sanitizer gate exists to prevent.
        override = os.environ.get("SPARK_EXAMPLES_TPU_NATIVE_SO")
        if override:
            try:
                lib = _bind(ctypes.CDLL(override))
            except OSError as e:
                raise OSError(
                    f"SPARK_EXAMPLES_TPU_NATIVE_SO={override!r} did not "
                    f"load: {e}"
                ) from e
            _lib = lib
            _tried = True
            return _lib
        _tried = True
        try:
            stale = not os.path.exists(_SO) or (
                os.path.getmtime(_SO) < os.path.getmtime(_SRC)
            )
        except OSError:
            # Source missing (e.g. a deployed tree shipping only the .so):
            # treat the existing library as current.
            stale = not os.path.exists(_SO)
        if stale and not _build():
            return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            return None
        _lib = _bind(lib)
        return _lib


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    """Declare the ctypes signatures on a freshly-dlopened library."""
    lib.pack_calls.argtypes = [
        ctypes.c_void_p,
        ctypes.c_void_p,
        ctypes.c_int64,
        ctypes.c_int64,
        ctypes.c_int64,
        ctypes.c_void_p,
    ]
    if hasattr(lib, "csr_to_packed_blocks"):
        # Absent from pre-PR-6 deployed .so files; callers probe
        # with hasattr and fall back to the numpy pack.
        lib.csr_to_packed_blocks.argtypes = [
            ctypes.c_void_p,
            ctypes.c_void_p,
            ctypes.c_int64,
            ctypes.c_int64,
            ctypes.c_int64,
            ctypes.c_void_p,
        ]
        lib.csr_to_packed_blocks.restype = ctypes.c_int64
    lib.murmur3_x64_128.argtypes = [
        ctypes.c_void_p,
        ctypes.c_int64,
        ctypes.c_uint64,
        ctypes.c_void_p,
    ]
    lib.murmur3_x64_128_batch.argtypes = [
        ctypes.c_void_p,
        ctypes.c_void_p,
        ctypes.c_int64,
        ctypes.c_uint64,
        ctypes.c_void_p,
    ]
    # Bind the cohort parser only when the library's struct layout
    # matches this module's ctypes mirror: a deployed tree may ship
    # an older .so, and reading an old struct through a newer layout
    # would silently misalign every pointer after the changed field.
    _ABI = 2
    abi_ok = False
    if hasattr(lib, "cohort_csr_abi_version"):
        lib.cohort_csr_abi_version.restype = ctypes.c_int64
        abi_ok = lib.cohort_csr_abi_version() == _ABI
    if abi_ok and hasattr(lib, "parse_cohort_jsonl"):
        lib.parse_cohort_jsonl.argtypes = [
            ctypes.c_char_p,
            ctypes.c_void_p,
            ctypes.c_void_p,
            ctypes.c_int64,
        ]
        lib.parse_cohort_jsonl.restype = ctypes.POINTER(CohortCsr)
        lib.cohort_csr_free.argtypes = [ctypes.POINTER(CohortCsr)]
    return lib


def native_available() -> bool:
    return load() is not None
