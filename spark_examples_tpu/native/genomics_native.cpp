// Native ingest core: the host-side hot loops of the data plane.
//
// The reference has no native code (pure JVM — SURVEY.md §2.9); the hot
// host loops there are JIT-compiled Scala. In this framework the host side
// is Python, so the two ingest-critical inner loops live here instead:
//
//   * pack_calls       — densify per-variant sample-index lists into the
//                        0/1 int8 genotype block consumed by the MXU path
//                        (the arrays/blocks.py fallback is a Python loop);
//   * murmur3 batch    — the cross-dataset variant identity hash
//                        (VariantsPca.scala:62-78 semantics), canonical
//                        MurmurHash3 x64-128, byte-identical to the pure
//                        Python implementation in genomics/hashing.py.
//
// Built by native/build.py with g++ -O3 -shared -fPIC; loaded via ctypes.
// Everything is extern "C" with flat POD buffers — no pybind11 dependency.

#include <cstdint>
#include <cstring>

extern "C" {

// out must be a zeroed (n_samples, stride) row-major int8 buffer with
// stride >= n_variants (the block may be column-padded).
// indices[offsets[v] .. offsets[v+1]) are the carrying sample rows of
// variant column v.
void pack_calls(const int64_t* indices, const int64_t* offsets,
                int64_t n_variants, int64_t n_samples, int64_t stride,
                int8_t* out) {
  for (int64_t v = 0; v < n_variants; ++v) {
    for (int64_t k = offsets[v]; k < offsets[v + 1]; ++k) {
      const int64_t s = indices[k];
      if (s >= 0 && s < n_samples) {
        out[s * stride + v] = 1;
      }
    }
  }
}

static inline uint64_t rotl64(uint64_t x, int8_t r) {
  return (x << r) | (x >> (64 - r));
}

static inline uint64_t fmix64(uint64_t k) {
  k ^= k >> 33;
  k *= 0xff51afd7ed558ccdULL;
  k ^= k >> 33;
  k *= 0xc4ceb9fe1a85ec53ULL;
  k ^= k >> 33;
  return k;
}

static inline uint64_t load64(const uint8_t* p) {
  uint64_t v;
  std::memcpy(&v, p, 8);
  return v;  // little-endian hosts only (x86-64 / aarch64)
}

void murmur3_x64_128(const uint8_t* data, int64_t len, uint64_t seed,
                     uint8_t* out16) {
  const int64_t nblocks = len / 16;
  uint64_t h1 = seed, h2 = seed;
  const uint64_t c1 = 0x87c37b91114253d5ULL;
  const uint64_t c2 = 0x4cf5ad432745937fULL;

  for (int64_t i = 0; i < nblocks; ++i) {
    uint64_t k1 = load64(data + i * 16);
    uint64_t k2 = load64(data + i * 16 + 8);

    k1 *= c1; k1 = rotl64(k1, 31); k1 *= c2; h1 ^= k1;
    h1 = rotl64(h1, 27); h1 += h2; h1 = h1 * 5 + 0x52dce729;

    k2 *= c2; k2 = rotl64(k2, 33); k2 *= c1; h2 ^= k2;
    h2 = rotl64(h2, 31); h2 += h1; h2 = h2 * 5 + 0x38495ab5;
  }

  const uint8_t* tail = data + nblocks * 16;
  const int64_t taillen = len & 15;
  uint64_t k1 = 0, k2 = 0;
  if (taillen > 8) {
    for (int64_t i = taillen - 1; i >= 8; --i) {
      k2 = (k2 << 8) | tail[i];
    }
    k2 *= c2; k2 = rotl64(k2, 33); k2 *= c1; h2 ^= k2;
  }
  if (taillen > 0) {
    const int64_t n1 = taillen < 8 ? taillen : 8;
    for (int64_t i = n1 - 1; i >= 0; --i) {
      k1 = (k1 << 8) | tail[i];
    }
    k1 *= c1; k1 = rotl64(k1, 31); k1 *= c2; h1 ^= k1;
  }

  h1 ^= static_cast<uint64_t>(len);
  h2 ^= static_cast<uint64_t>(len);
  h1 += h2;
  h2 += h1;
  h1 = fmix64(h1);
  h2 = fmix64(h2);
  h1 += h2;
  h2 += h1;

  std::memcpy(out16, &h1, 8);
  std::memcpy(out16 + 8, &h2, 8);
}

// Hash n concatenated byte strings; string i spans
// data[offsets[i] .. offsets[i+1]). out is n * 16 bytes.
void murmur3_x64_128_batch(const uint8_t* data, const int64_t* offsets,
                           int64_t n, uint64_t seed, uint8_t* out) {
  for (int64_t i = 0; i < n; ++i) {
    murmur3_x64_128(data + offsets[i], offsets[i + 1] - offsets[i], seed,
                    out + i * 16);
  }
}

}  // extern "C"
