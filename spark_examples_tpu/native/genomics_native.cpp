// Native ingest core: the host-side hot loops of the data plane.
//
// The reference has no native code (pure JVM — SURVEY.md §2.9); the hot
// host loops there are JIT-compiled Scala. In this framework the host side
// is Python, so the two ingest-critical inner loops live here instead:
//
//   * pack_calls       — densify per-variant sample-index lists into the
//                        0/1 int8 genotype block consumed by the MXU path
//                        (the arrays/blocks.py fallback is a Python loop);
//   * murmur3 batch    — the cross-dataset variant identity hash
//                        (VariantsPca.scala:62-78 semantics), canonical
//                        MurmurHash3 x64-128, byte-identical to the pure
//                        Python implementation in genomics/hashing.py.
//
// Built by native/build.py with g++ -O3 -shared -fPIC; loaded via ctypes.
// Everything is extern "C" with flat POD buffers — no pybind11 dependency.

#include <cstdint>
#include <cstring>

extern "C" {

// out must be a zeroed (n_samples, stride) row-major int8 buffer with
// stride >= n_variants (the block may be column-padded).
// indices[offsets[v] .. offsets[v+1]) are the carrying sample rows of
// variant column v.
void pack_calls(const int64_t* indices, const int64_t* offsets,
                int64_t n_variants, int64_t n_samples, int64_t stride,
                int8_t* out) {
  for (int64_t v = 0; v < n_variants; ++v) {
    for (int64_t k = offsets[v]; k < offsets[v + 1]; ++k) {
      const int64_t s = indices[k];
      if (s >= 0 && s < n_samples) {
        out[s * stride + v] = 1;
      }
    }
  }
}

// Scatter one CSR window straight into a BIT-PACKED block: sample s
// carrying variant column v sets bit (0x80 >> (v & 7)) of byte
// out[s * stride_bytes + (v >> 3)] — np.packbits bit order (MSB first),
// so the output is byte-identical to
// np.packbits(densify(indices, offsets), axis=1). Skipping the int8
// densify intermediate is 8x less memory traffic on the hottest host
// loop of ingest (PERFORMANCE.md round-5: 38.7 s single-threaded).
// out must be a zeroed (n_samples, stride_bytes) row-major uint8 buffer
// with stride_bytes >= ceil(n_variants / 8) (column-padded blocks keep
// their pad bits zero — inert in the Gramian).
// Returns 0, or 1 when any index falls outside [0, n_samples) — the
// caller raises; a silent skip would drop a carrier from G.
int64_t csr_to_packed_blocks(const int64_t* indices, const int64_t* offsets,
                             int64_t n_variants, int64_t n_samples,
                             int64_t stride_bytes, uint8_t* out) {
  for (int64_t v = 0; v < n_variants; ++v) {
    const int64_t byte = v >> 3;
    const uint8_t bit = static_cast<uint8_t>(0x80u >> (v & 7));
    for (int64_t k = offsets[v]; k < offsets[v + 1]; ++k) {
      const int64_t s = indices[k];
      if (s < 0 || s >= n_samples) {
        return 1;
      }
      out[s * stride_bytes + byte] |= bit;
    }
  }
  return 0;
}

static inline uint64_t rotl64(uint64_t x, int8_t r) {
  return (x << r) | (x >> (64 - r));
}

static inline uint64_t fmix64(uint64_t k) {
  k ^= k >> 33;
  k *= 0xff51afd7ed558ccdULL;
  k ^= k >> 33;
  k *= 0xc4ceb9fe1a85ec53ULL;
  k ^= k >> 33;
  return k;
}

static inline uint64_t load64(const uint8_t* p) {
  uint64_t v;
  std::memcpy(&v, p, 8);
  return v;  // little-endian hosts only (x86-64 / aarch64)
}

void murmur3_x64_128(const uint8_t* data, int64_t len, uint64_t seed,
                     uint8_t* out16) {
  const int64_t nblocks = len / 16;
  uint64_t h1 = seed, h2 = seed;
  const uint64_t c1 = 0x87c37b91114253d5ULL;
  const uint64_t c2 = 0x4cf5ad432745937fULL;

  for (int64_t i = 0; i < nblocks; ++i) {
    uint64_t k1 = load64(data + i * 16);
    uint64_t k2 = load64(data + i * 16 + 8);

    k1 *= c1; k1 = rotl64(k1, 31); k1 *= c2; h1 ^= k1;
    h1 = rotl64(h1, 27); h1 += h2; h1 = h1 * 5 + 0x52dce729;

    k2 *= c2; k2 = rotl64(k2, 33); k2 *= c1; h2 ^= k2;
    h2 = rotl64(h2, 31); h2 += h1; h2 = h2 * 5 + 0x38495ab5;
  }

  const uint8_t* tail = data + nblocks * 16;
  const int64_t taillen = len & 15;
  uint64_t k1 = 0, k2 = 0;
  if (taillen > 8) {
    for (int64_t i = taillen - 1; i >= 8; --i) {
      k2 = (k2 << 8) | tail[i];
    }
    k2 *= c2; k2 = rotl64(k2, 33); k2 *= c1; h2 ^= k2;
  }
  if (taillen > 0) {
    const int64_t n1 = taillen < 8 ? taillen : 8;
    for (int64_t i = n1 - 1; i >= 0; --i) {
      k1 = (k1 << 8) | tail[i];
    }
    k1 *= c1; k1 = rotl64(k1, 31); k1 *= c2; h1 ^= k1;
  }

  h1 ^= static_cast<uint64_t>(len);
  h2 ^= static_cast<uint64_t>(len);
  h1 += h2;
  h2 += h1;
  h1 = fmix64(h1);
  h2 = fmix64(h2);
  h1 += h2;
  h2 += h1;

  std::memcpy(out16, &h1, 8);
  std::memcpy(out16 + 8, &h2, 8);
}

// Hash n concatenated byte strings; string i spans
// data[offsets[i] .. offsets[i+1]). out is n * 16 bytes.
void murmur3_x64_128_batch(const uint8_t* data, const int64_t* offsets,
                           int64_t n, uint64_t seed, uint8_t* out) {
  for (int64_t i = 0; i < n; ++i) {
    murmur3_x64_128(data + offsets[i], offsets[i + 1] - offsets[i], seed,
                    out + i * 16);
  }
}

}  // extern "C"

// ---------------------------------------------------------------------------
// JSONL cohort parser — the cold-ingest hot loop.
//
// Parses <dir>/variants.jsonl into file-ordered CSR arrays for the
// columnar sidecar (genomics/sources.py _CsrCohort): per contig-kept
// record its normalized contig code, start, variant-set code, AF value,
// and the carrying callset ordinals (any genotype allele > 0), matching
// the Python parse loop exactly. Python's json.loads dominated cold
// sidecar builds (~60s of 79s at 2504x32k); this replaces it.
//
// Correct-but-conservative contract: the parser handles the cohort
// interchange schema (json.dumps output: one object per line, \uXXXX and
// exotic constructs absent from ids we extract). ANY anomaly — an escape
// in an extracted string, unknown callset id, malformed JSON — aborts
// with an error code and the caller falls back to the Python parser, so
// the native path can be fast without ever being wrong.
// ---------------------------------------------------------------------------

#include <cmath>
#include <cstdio>
#include <cstdlib>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

// Strict JSON number grammar (plus json.loads' Infinity/-Infinity/NaN
// extensions) — strtod alone accepts hex floats ("0x10") that
// json.loads rejects, which would let the native parser silently accept
// files the Python fallback raises on.
bool json_number_valid(const std::string& t) {
  if (t == "Infinity" || t == "-Infinity" || t == "NaN") return true;
  size_t i = 0;
  if (i < t.size() && t[i] == '-') ++i;
  if (i >= t.size()) return false;
  if (t[i] == '0') {
    ++i;
  } else if (t[i] >= '1' && t[i] <= '9') {
    while (i < t.size() && t[i] >= '0' && t[i] <= '9') ++i;
  } else {
    return false;
  }
  if (i < t.size() && t[i] == '.') {
    ++i;
    if (i >= t.size() || t[i] < '0' || t[i] > '9') return false;
    while (i < t.size() && t[i] >= '0' && t[i] <= '9') ++i;
  }
  if (i < t.size() && (t[i] == 'e' || t[i] == 'E')) {
    ++i;
    if (i < t.size() && (t[i] == '+' || t[i] == '-')) ++i;
    if (i >= t.size() || t[i] < '0' || t[i] > '9') return false;
    while (i < t.size() && t[i] >= '0' && t[i] <= '9') ++i;
  }
  return i == t.size();
}

struct LineParser {
  const char* p;
  const char* end;
  bool err = false;

  void ws() {
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\r')) ++p;
  }
  bool eat(char c) {
    ws();
    if (p < end && *p == c) {
      ++p;
      return true;
    }
    err = true;
    return false;
  }
  bool peek(char c) {
    ws();
    return p < end && *p == c;
  }

  // Extracted strings must be escape-free (ids/contigs in the schema
  // are); any backslash is an anomaly -> whole-file Python fallback.
  bool string_exact(std::string* out) {
    if (!eat('"')) return false;
    const char* s = p;
    while (p < end && *p != '"') {
      if (*p == '\\') {
        err = true;
        return false;
      }
      ++p;
    }
    if (p >= end) {
      err = true;
      return false;
    }
    out->assign(s, p - s);
    ++p;
    return true;
  }

  void skip_string() {
    if (!eat('"')) return;
    while (p < end && *p != '"') {
      if (*p == '\\' && p + 1 < end) ++p;
      ++p;
    }
    if (p >= end) {
      err = true;
      return;
    }
    ++p;
  }

  void skip_value() {
    ws();
    if (p >= end) {
      err = true;
      return;
    }
    char c = *p;
    if (c == '"') {
      skip_string();
    } else if (c == '{') {
      ++p;
      if (peek('}')) {
        ++p;
        return;
      }
      while (!err) {
        skip_string();  // key
        if (!eat(':')) return;
        skip_value();
        ws();
        if (p < end && *p == ',') {
          ++p;
          continue;
        }
        eat('}');
        return;
      }
    } else if (c == '[') {
      ++p;
      if (peek(']')) {
        ++p;
        return;
      }
      while (!err) {
        skip_value();
        ws();
        if (p < end && *p == ',') {
          ++p;
          continue;
        }
        eat(']');
        return;
      }
    } else {
      // number / true / false / null — validated, so invalid JSON that
      // json.loads would reject always falls back rather than silently
      // diverging between native and Python builds.
      const char* s = p;
      while (p < end && *p != ',' && *p != '}' && *p != ']' &&
             *p != ' ' && *p != '\t' && *p != '\r') {
        ++p;
      }
      std::string tok(s, p - s);
      if (tok == "true" || tok == "false" || tok == "null") return;
      if (!json_number_valid(tok)) err = true;
    }
  }

  bool number_i64(int64_t* out) {
    ws();
    const char* s = p;
    if (p < end && *p == '-') ++p;
    const char* d0 = p;
    while (p < end && *p >= '0' && *p <= '9') ++p;
    // Strict JSON integer: digits required, no leading zeros — strtoll
    // alone would accept "012", which json.loads rejects.
    if (p == d0 || (*d0 == '0' && p - d0 > 1)) {
      err = true;
      return false;
    }
    *out = std::strtoll(std::string(s, p - s).c_str(), nullptr, 10);
    return true;
  }

  // AF: a number, or a string holding one; non-numeric -> NaN (the
  // sidecar's documented missing-value semantic).
  double af_value() {
    ws();
    if (p < end && *p == '"') {
      std::string s;
      if (!string_exact(&s)) return NAN;
      // Mirror Python float(str) without reimplementing it: strict JSON
      // numbers parse, the common missing markers "." and "" map to NaN
      // (float() raises on them), and anything else — strings float()
      // might still accept under wider rules ("1_5", " 0.5", "inf") —
      // refuses the file so the Python parser decides.
      if (s.empty() || s == ".") return NAN;
      if (!json_number_valid(s)) {
        err = true;
        return NAN;
      }
      return std::strtod(s.c_str(), nullptr);
    }
    const char* s = p;
    skip_value();  // validates the bare token (err on invalid JSON)
    if (err) return NAN;
    std::string tmp(s, p - s);
    if (tmp == "null") return NAN;
    if (!json_number_valid(tmp)) {
      err = true;  // not a JSON number: json.loads would reject the line
      return NAN;
    }
    return std::strtod(tmp.c_str(), nullptr);
  }
};

// "[a-z]*[0-9]*" fullmatch -> digit part, or npos-flag when dropped
// (types.py normalize_contig semantics, VariantsRDD.scala:103-110).
bool normalize_contig(const std::string& name, std::string* out) {
  size_t i = 0;
  while (i < name.size() && name[i] >= 'a' && name[i] <= 'z') ++i;
  size_t d = i;
  while (d < name.size() && name[d] >= '0' && name[d] <= '9') ++d;
  if (d != name.size()) return false;  // anything else anywhere: drop
  out->assign(name, i, d - i);
  return true;
}

struct Interner {
  std::unordered_map<std::string, int32_t> codes;
  std::string blob;
  std::vector<int64_t> offs{0};
  int32_t intern(const std::string& s) {
    auto it = codes.find(s);
    if (it != codes.end()) return it->second;
    int32_t code = static_cast<int32_t>(codes.size());
    codes.emplace(s, code);
    blob += s;
    offs.push_back(static_cast<int64_t>(blob.size()));
    return code;
  }
};

}  // namespace

extern "C" {

typedef struct CohortCsr {
  int64_t n_variants;
  int64_t n_calls;
  int64_t n_contigs;
  int64_t n_vsids;
  // 0 ok; 1 parse anomaly — including unknown callset ids, which only
  // the Python parser's extra-id interning handles (caller falls back);
  // 2 IO error.
  int64_t error;
  int64_t error_line;
  const int64_t* starts;
  const int64_t* ends;
  const int32_t* contig_code;
  const int32_t* vsid_code;
  const double* afs;
  const int64_t* offsets;
  const int32_t* ords;
  const char* contig_blob;
  const int64_t* contig_offs;
  const char* vsid_blob;
  const int64_t* vsid_offs;
  // Per-record identity fields (cross-dataset join): reference bases and
  // concatenated alternate bases, offsets length n_variants + 1.
  const char* ref_blob;
  const int64_t* ref_offs;
  const char* alt_blob;
  const int64_t* alt_offs;
} CohortCsr;

}  // extern "C"

namespace {

struct CohortImpl {
  CohortCsr view{};
  std::vector<int64_t> starts;
  std::vector<int64_t> ends;
  std::vector<int32_t> contig_code;
  std::vector<int32_t> vsid_code;
  std::vector<double> afs;
  std::vector<int64_t> offsets{0};
  std::vector<int32_t> ords;
  Interner contigs;
  Interner vsids;
  std::string ref_blob;
  std::vector<int64_t> ref_offs{0};
  std::string alt_blob;
  std::vector<int64_t> alt_offs{0};

  void finalize() {
    view.n_variants = static_cast<int64_t>(starts.size());
    view.n_calls = static_cast<int64_t>(ords.size());
    view.n_contigs = static_cast<int64_t>(contigs.codes.size());
    view.n_vsids = static_cast<int64_t>(vsids.codes.size());
    view.starts = starts.data();
    view.ends = ends.data();
    view.contig_code = contig_code.data();
    view.vsid_code = vsid_code.data();
    view.afs = afs.data();
    view.offsets = offsets.data();
    view.ords = ords.data();
    view.contig_blob = contigs.blob.data();
    view.contig_offs = contigs.offs.data();
    view.vsid_blob = vsids.blob.data();
    view.vsid_offs = vsids.offs.data();
    view.ref_blob = ref_blob.data();
    view.ref_offs = ref_offs.data();
    view.alt_blob = alt_blob.data();
    view.alt_offs = alt_offs.data();
  }
};

// Parse one record line; returns false on anomaly (err set).
bool parse_line(const char* line, const char* line_end, CohortImpl* out,
                const std::unordered_map<std::string, int32_t>& ord_of) {
  LineParser lp{line, line_end};
  if (!lp.eat('{')) return false;
  std::string contig;
  bool contig_seen = false, dropped = false;
  int64_t start = 0, end_pos = 0;
  bool start_seen = false, end_seen = false;
  std::string vsid;
  std::string ref_bases;
  std::string alt_concat;
  bool ref_seen = false, alt_seen = false;
  double af = NAN;
  std::vector<int32_t> row_ords;
  // json.loads applies last-wins to duplicate keys; the native parser
  // would accumulate/first-win instead — refuse duplicates of any key it
  // extracts so the two builds can never diverge.
  bool seen_vsid = false, seen_info = false, seen_calls = false;

  if (lp.peek('}')) {
    lp.err = true;  // empty record: not the schema
    return false;
  }
  while (!lp.err) {
    std::string key;
    if (!lp.string_exact(&key)) return false;
    if (!lp.eat(':')) return false;
    if (key == "reference_name") {
      if (contig_seen) {
        lp.err = true;
        return false;
      }
      std::string name;
      if (!lp.string_exact(&name)) return false;
      contig_seen = true;
      dropped = !normalize_contig(name, &contig);
    } else if (key == "start") {
      if (start_seen) {
        lp.err = true;
        return false;
      }
      if (!lp.number_i64(&start)) return false;
      start_seen = true;
    } else if (key == "end") {
      if (end_seen) {
        lp.err = true;
        return false;
      }
      if (!lp.number_i64(&end_pos)) return false;
      end_seen = true;
    } else if (key == "reference_bases") {
      if (ref_seen) {
        lp.err = true;
        return false;
      }
      if (lp.peek('"')) {
        if (!lp.string_exact(&ref_bases)) return false;
      } else if (lp.peek('n')) {
        lp.skip_value();  // null -> "" (payload semantics)
      } else {
        // Non-schema type (number/bool/object): the Python paths treat
        // these as invalid identities — fall back, never coerce.
        lp.err = true;
        return false;
      }
      ref_seen = true;
    } else if (key == "alternate_bases") {
      if (alt_seen) {
        lp.err = true;
        return false;
      }
      alt_seen = true;
      lp.ws();
      if (lp.p < lp.end && *lp.p == '[') {
        ++lp.p;
        if (lp.peek(']')) {
          ++lp.p;
        } else {
          while (!lp.err) {
            std::string alt;
            if (!lp.string_exact(&alt)) return false;
            alt_concat += alt;  // payload concatenates alternates
            lp.ws();
            if (lp.p < lp.end && *lp.p == ',') {
              ++lp.p;
              continue;
            }
            lp.eat(']');
            break;
          }
        }
      } else if (lp.peek('n')) {
        lp.skip_value();  // null -> "" (payload semantics)
      } else {
        // A bare string/number here diverges from Python's join
        // semantics — refuse, never coerce.
        lp.err = true;
        return false;
      }
    } else if (key == "variant_set_id") {
      if (seen_vsid) {
        lp.err = true;
        return false;
      }
      seen_vsid = true;
      if (lp.peek('"')) {
        if (!lp.string_exact(&vsid)) return false;
      } else {
        // Explicit null: a falsy stored id is a wildcard under the one
        // variant-set rule, same as missing — keep vsid "".
        lp.skip_value();
      }
    } else if (key == "info") {
      if (seen_info) {
        lp.err = true;
        return false;
      }
      seen_info = true;
      if (!lp.eat('{')) return false;
      if (lp.peek('}')) {
        ++lp.p;
      } else {
        while (!lp.err) {
          std::string ikey;
          if (!lp.string_exact(&ikey)) return false;
          if (!lp.eat(':')) return false;
          if (ikey == "AF") {
            if (!std::isnan(af)) {  // duplicate AF key
              lp.err = true;
              return false;
            }
            if (!lp.eat('[')) return false;
            if (lp.peek(']')) {
              ++lp.p;
            } else {
              af = lp.af_value();
              while (!lp.err) {
                lp.ws();
                if (lp.p < lp.end && *lp.p == ',') {
                  ++lp.p;
                  lp.skip_value();
                  continue;
                }
                lp.eat(']');
                break;
              }
            }
          } else {
            lp.skip_value();
          }
          lp.ws();
          if (lp.p < lp.end && *lp.p == ',') {
            ++lp.p;
            continue;
          }
          lp.eat('}');
          break;
        }
      }
    } else if (key == "calls") {
      if (seen_calls) {
        lp.err = true;
        return false;
      }
      seen_calls = true;
      if (!lp.eat('[')) return false;
      if (lp.peek(']')) {
        ++lp.p;
      } else {
        while (!lp.err) {  // one call object per iteration
          if (!lp.eat('{')) return false;
          std::string cid;
          bool cid_seen = false, carries = false, gt_seen = false;
          if (lp.peek('}')) {
            ++lp.p;
          } else {
            while (!lp.err) {
              std::string ckey;
              if (!lp.string_exact(&ckey)) return false;
              if (!lp.eat(':')) return false;
              if (ckey == "callset_id") {
                if (cid_seen) {  // duplicate key
                  lp.err = true;
                  return false;
                }
                if (!lp.string_exact(&cid)) return false;
                cid_seen = true;
              } else if (ckey == "genotype") {
                if (gt_seen) {  // duplicate key
                  lp.err = true;
                  return false;
                }
                gt_seen = true;
                if (!lp.eat('[')) return false;
                if (lp.peek(']')) {
                  ++lp.p;
                } else {
                  while (!lp.err) {
                    int64_t g;
                    if (!lp.number_i64(&g)) return false;
                    if (g > 0) carries = true;
                    lp.ws();
                    if (lp.p < lp.end && *lp.p == ',') {
                      ++lp.p;
                      continue;
                    }
                    lp.eat(']');
                    break;
                  }
                }
              } else {
                lp.skip_value();
              }
              lp.ws();
              if (lp.p < lp.end && *lp.p == ',') {
                ++lp.p;
                continue;
              }
              lp.eat('}');
              break;
            }
          }
          if (lp.err) return false;
          if (carries) {
            if (!cid_seen) {
              lp.err = true;
              return false;
            }
            auto it = ord_of.find(cid);
            if (it == ord_of.end()) {
              // Unknown callset: fall back to the Python parser, which
              // interns it into the extra-id table for lazy per-query
              // KeyError semantics.
              lp.err = true;
              return false;
            }
            row_ords.push_back(it->second);
          }
          lp.ws();
          if (lp.p < lp.end && *lp.p == ',') {
            ++lp.p;
            continue;
          }
          lp.eat(']');
          break;
        }
      }
    } else {
      lp.skip_value();
    }
    if (lp.err) return false;
    lp.ws();
    if (lp.p < lp.end && *lp.p == ',') {
      ++lp.p;
      continue;
    }
    if (!lp.eat('}')) return false;
    break;
  }
  if (lp.err) return false;
  lp.ws();
  if (lp.p != lp.end) {  // trailing garbage on the line
    return false;
  }
  if (!contig_seen || !start_seen || !end_seen) return false;
  if (dropped) return true;  // non-numeric contig: skip, no error
  out->contig_code.push_back(out->contigs.intern(contig));
  out->starts.push_back(start);
  out->ends.push_back(end_pos);
  out->vsid_code.push_back(out->vsids.intern(vsid));
  out->afs.push_back(af);
  out->ref_blob += ref_bases;
  out->ref_offs.push_back(static_cast<int64_t>(out->ref_blob.size()));
  out->alt_blob += alt_concat;
  out->alt_offs.push_back(static_cast<int64_t>(out->alt_blob.size()));
  out->ords.insert(out->ords.end(), row_ords.begin(), row_ords.end());
  out->offsets.push_back(static_cast<int64_t>(out->ords.size()));
  return true;
}

}  // namespace

namespace {

// Parse the byte range [begin, range_end) of the file (range_end < 0 =
// to EOF). Non-final ranges end immediately after a newline (the caller
// aligns them), so every line is complete. Returns 0 ok, 1 parse
// anomaly, 2 IO error; *lines counts lines consumed.
int parse_range(const char* path, int64_t begin, int64_t range_end,
                CohortImpl* impl,
                const std::unordered_map<std::string, int32_t>& ord_of,
                int64_t* lines) {
  FILE* f = std::fopen(path, "rb");
  if (f == nullptr) return 2;
  if (begin > 0 && std::fseek(f, static_cast<long>(begin), SEEK_SET) != 0) {
    std::fclose(f);
    return 2;
  }
  const size_t CHUNK = 8 << 20;
  int64_t budget =
      range_end < 0 ? -1 : range_end - begin;  // -1 = unbounded
  std::vector<char> buf;
  size_t have = 0;
  int64_t line_no = 0;
  bool eof = false;
  while (!eof || have > 0) {
    size_t want = CHUNK;
    if (budget >= 0 && static_cast<int64_t>(want) > budget) {
      want = static_cast<size_t>(budget);
    }
    buf.resize(have + want + 1);
    size_t got = want ? std::fread(buf.data() + have, 1, want, f) : 0;
    if (got < want && std::ferror(f)) {
      // A mid-file read error must not masquerade as EOF: a silently
      // truncated parse would be cached as a valid sidecar.
      std::fclose(f);
      return 2;
    }
    if (budget >= 0) budget -= static_cast<int64_t>(got);
    eof = (budget == 0) || got < want;
    have += got;
    size_t sentinel_pos = SIZE_MAX;
    if (eof) {
      // Sentinel newline: terminates a final unterminated line (an extra
      // blank line is skipped below) and guarantees every strtoll/strtod
      // inside a line stops before leaving initialized data.
      buf.resize(have + 1);
      buf[have] = '\n';
      sentinel_pos = have;
      have += 1;
    }
    size_t line_start = 0;
    for (;;) {
      const char* nl = static_cast<const char*>(
          memchr(buf.data() + line_start, '\n', have - line_start));
      if (nl == nullptr) break;
      const char* line = buf.data() + line_start;
      const char* line_end = nl;
      // The empty line "terminated" by the sentinel is not data — it
      // must not shift line numbers (merged threaded counts would
      // overshoot by one per range).
      const bool synthetic =
          static_cast<size_t>(nl - buf.data()) == sentinel_pos &&
          line == line_end;
      if (!synthetic) ++line_no;
      bool blank = true;
      for (const char* q = line; q < line_end; ++q) {
        if (*q != ' ' && *q != '\t' && *q != '\r') {
          blank = false;
          break;
        }
      }
      if (!blank && !parse_line(line, line_end, impl, ord_of)) {
        std::fclose(f);
        *lines = line_no;
        return 1;
      }
      line_start = static_cast<size_t>(nl - buf.data()) + 1;
      if (line_start >= have) break;
    }
    if (line_start > 0) {
      std::memmove(buf.data(), buf.data() + line_start, have - line_start);
      have -= line_start;
    }
    if (eof) break;
  }
  std::fclose(f);
  *lines = line_no;
  return 0;
}

// Append src's arrays onto dst, re-coding interned contig/vsid ids
// through dst's interners — chunk order makes the merged tables equal to
// a sequential parse's first-encounter order, so threading is
// bit-invisible in the output.
void merge_chunk(CohortImpl* dst, const CohortImpl& src) {
  std::vector<int32_t> cmap(src.contigs.codes.size());
  for (size_t i = 0; i + 1 < src.contigs.offs.size(); ++i) {
    cmap[i] = dst->contigs.intern(std::string(
        src.contigs.blob.data() + src.contigs.offs[i],
        static_cast<size_t>(src.contigs.offs[i + 1] - src.contigs.offs[i])));
  }
  std::vector<int32_t> vmap(src.vsids.codes.size());
  for (size_t i = 0; i + 1 < src.vsids.offs.size(); ++i) {
    vmap[i] = dst->vsids.intern(std::string(
        src.vsids.blob.data() + src.vsids.offs[i],
        static_cast<size_t>(src.vsids.offs[i + 1] - src.vsids.offs[i])));
  }
  for (int32_t c : src.contig_code) dst->contig_code.push_back(cmap[c]);
  for (int32_t v : src.vsid_code) dst->vsid_code.push_back(vmap[v]);
  dst->starts.insert(dst->starts.end(), src.starts.begin(),
                     src.starts.end());
  dst->ends.insert(dst->ends.end(), src.ends.begin(), src.ends.end());
  dst->afs.insert(dst->afs.end(), src.afs.begin(), src.afs.end());
  const int64_t ord_base = static_cast<int64_t>(dst->ords.size());
  dst->ords.insert(dst->ords.end(), src.ords.begin(), src.ords.end());
  for (size_t i = 1; i < src.offsets.size(); ++i) {
    dst->offsets.push_back(src.offsets[i] + ord_base);
  }
  const int64_t ref_base = static_cast<int64_t>(dst->ref_blob.size());
  dst->ref_blob += src.ref_blob;
  for (size_t i = 1; i < src.ref_offs.size(); ++i) {
    dst->ref_offs.push_back(src.ref_offs[i] + ref_base);
  }
  const int64_t alt_base = static_cast<int64_t>(dst->alt_blob.size());
  dst->alt_blob += src.alt_blob;
  for (size_t i = 1; i < src.alt_offs.size(); ++i) {
    dst->alt_offs.push_back(src.alt_offs[i] + alt_base);
  }
}

}  // namespace

extern "C" {

CohortCsr* parse_cohort_jsonl(const char* path, const uint8_t* callset_blob,
                              const int64_t* callset_offs,
                              int64_t n_callsets) {
  auto* impl = new CohortImpl;
  std::unordered_map<std::string, int32_t> ord_of;
  ord_of.reserve(static_cast<size_t>(n_callsets) * 2);
  for (int64_t i = 0; i < n_callsets; ++i) {
    ord_of.emplace(
        std::string(
            reinterpret_cast<const char*>(callset_blob) + callset_offs[i],
            static_cast<size_t>(callset_offs[i + 1] - callset_offs[i])),
        static_cast<int32_t>(i));
  }

  // Thread count: hardware up to 8 (merge is cheap; parse scales), one
  // range per >=32MB so small files stay sequential. Env override
  // SPARK_EXAMPLES_TPU_PARSE_THREADS for tests/tuning.
  int64_t size = -1;
  {
    FILE* f = std::fopen(path, "rb");
    if (f != nullptr) {
      if (std::fseek(f, 0, SEEK_END) == 0) size = std::ftell(f);
      std::fclose(f);
    }
  }
  int threads = 0;
  bool forced = false;
  if (const char* env = std::getenv("SPARK_EXAMPLES_TPU_PARSE_THREADS")) {
    threads = std::atoi(env);
    forced = threads > 0;  // explicit override skips the size clamp so
                           // tests can exercise the threaded path on
                           // small fixtures
    if (threads > 64) threads = 64;  // a absurd override must not spawn
                                     // unbounded threads (a failed
                                     // std::thread ctor would terminate
                                     // the embedding interpreter)
  }
  if (threads <= 0) {
    threads = static_cast<int>(std::thread::hardware_concurrency());
    if (threads > 8) threads = 8;
  }
  if (!forced && size >= 0) {
    const int64_t per = size / (32 << 20);
    if (per < threads) threads = static_cast<int>(per);
  }
  if (threads < 1) threads = 1;

  if (threads == 1 || size <= 0) {
    int64_t lines = 0;
    int rc = parse_range(path, 0, -1, impl, ord_of, &lines);
    if (rc != 0) {
      impl->view.error = rc;
      impl->view.error_line = rc == 1 ? lines : -1;
    }
    impl->finalize();
    return &impl->view;
  }

  // Split at line boundaries: advance each target offset to just past
  // the next newline.
  std::vector<int64_t> bounds{0};
  {
    FILE* f = std::fopen(path, "rb");
    if (f == nullptr) {
      impl->view.error = 2;
      impl->finalize();
      return &impl->view;
    }
    std::vector<char> probe(1 << 20);
    for (int i = 1; i < threads; ++i) {
      int64_t target = size * i / threads;
      if (target <= bounds.back()) continue;
      if (std::fseek(f, static_cast<long>(target), SEEK_SET) != 0) break;
      size_t got = std::fread(probe.data(), 1, probe.size(), f);
      const char* nl =
          static_cast<const char*>(memchr(probe.data(), '\n', got));
      if (nl == nullptr) continue;  // giant line: fold into next range
      bounds.push_back(target + (nl - probe.data()) + 1);
    }
    std::fclose(f);
  }
  bounds.push_back(-1);  // last range: to EOF

  const size_t n_ranges = bounds.size() - 1;
  std::vector<CohortImpl> chunks(n_ranges);
  std::vector<int> rcs(n_ranges, 0);
  std::vector<int64_t> lines(n_ranges, 0);
  std::vector<std::thread> workers;
  workers.reserve(n_ranges);
  for (size_t i = 0; i < n_ranges; ++i) {
    workers.emplace_back([&, i]() {
      rcs[i] = parse_range(path, bounds[i], bounds[i + 1], &chunks[i],
                           ord_of, &lines[i]);
    });
  }
  for (auto& w : workers) w.join();
  for (size_t i = 0; i < n_ranges; ++i) {
    if (rcs[i] != 0) {
      impl->view.error = rcs[i];
      // Global line number: lines of completed ranges before the
      // failing one plus its local count.
      int64_t base = 0;
      for (size_t j = 0; j < i; ++j) base += lines[j];
      impl->view.error_line = rcs[i] == 1 ? base + lines[i] : -1;
      impl->finalize();
      return &impl->view;
    }
  }
  {
    size_t nv = 0, nords = 0, nref = 0, nalt = 0;
    for (const auto& c : chunks) {
      nv += c.starts.size();
      nords += c.ords.size();
      nref += c.ref_blob.size();
      nalt += c.alt_blob.size();
    }
    impl->starts.reserve(nv);
    impl->ends.reserve(nv);
    impl->contig_code.reserve(nv);
    impl->vsid_code.reserve(nv);
    impl->afs.reserve(nv);
    impl->offsets.reserve(nv + 1);
    impl->ords.reserve(nords);
    impl->ref_offs.reserve(nv + 1);
    impl->alt_offs.reserve(nv + 1);
    impl->ref_blob.reserve(nref);
    impl->alt_blob.reserve(nalt);
  }
  for (auto& chunk : chunks) {
    merge_chunk(impl, chunk);
    chunk = CohortImpl{};  // free as we go: peak ~= data + one chunk
  }
  impl->finalize();
  return &impl->view;
}

void cohort_csr_free(CohortCsr* c) {
  delete reinterpret_cast<CohortImpl*>(c);
}

// Struct-layout handshake: the loader binds parse_cohort_jsonl only when
// this matches its expected value, so a stale deployed .so can never be
// read through a newer (misaligned) ctypes layout.
int64_t cohort_csr_abi_version() { return 2; }

}  // extern "C"
